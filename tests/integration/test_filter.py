"""WHERE tests (reference: tests/integration/test_filter.py)."""
import pandas as pd

from tests.conftest import assert_eq


def test_filter(c, df):
    assert_eq(c.sql("SELECT * FROM df WHERE a < 2"), df[df["a"] < 2])


def test_filter_scalar(c, df):
    assert_eq(c.sql("SELECT * FROM df WHERE True"), df)
    assert_eq(c.sql("SELECT * FROM df WHERE False"), df.head(0))
    assert_eq(c.sql("SELECT * FROM df WHERE (1 = 1)"), df)
    assert_eq(c.sql("SELECT * FROM df WHERE (1 = 0)"), df.head(0))


def test_filter_complicated(c, df):
    expected = df[((df["a"] < 3) & ((df["b"] > 1) & (df["b"] < 3)))]
    assert_eq(c.sql("SELECT * FROM df WHERE a < 3 AND (b > 1 AND b < 3)"), expected)


def test_filter_with_nan(c, user_table_nan):
    result = c.sql("SELECT * FROM user_table_nan WHERE c = 3").to_pandas()
    assert list(result["c"]) == [3]


def test_string_filter(c, string_table):
    assert_eq(
        c.sql("SELECT * FROM string_table WHERE a = 'a normal string'"),
        string_table.head(1),
    )


def test_filter_or(c, df):
    expected = df[(df["a"] < 2) | (df["b"] > 9)]
    assert_eq(c.sql("SELECT * FROM df WHERE a < 2 OR b > 9"), expected)


def test_filter_not(c, df):
    expected = df[~(df["a"] < 2)]
    assert_eq(c.sql("SELECT * FROM df WHERE NOT a < 2"), expected)


def test_filter_between(c, df):
    expected = df[df["b"].between(2, 4)]
    assert_eq(c.sql("SELECT * FROM df WHERE b BETWEEN 2 AND 4"), expected)


def test_filter_in(c, user_table_1):
    expected = user_table_1[user_table_1["user_id"].isin([1, 3])]
    assert_eq(
        c.sql("SELECT * FROM user_table_1 WHERE user_id IN (1, 3)"),
        expected, check_row_order=False,
    )


def test_filter_null_propagation(c, user_table_nan):
    # NULL comparisons are filtered out (three-valued logic)
    result = c.sql("SELECT * FROM user_table_nan WHERE c > 0").to_pandas()
    assert sorted(result["c"]) == [1, 3]
