"""Integration tests for incremental materialized views (ISSUE 14):
pandas-oracle parity across append sequences, the overwrite staleness
regression, counter reconciliation, the refresh chaos site, and the
DSQL_MV=0 baseline."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import faults, telemetry as _tel
from dask_sql_tpu.runtime.resilience import UserError

from tests.conftest import assert_eq


@pytest.fixture(autouse=True)
def _cache_on(monkeypatch):
    # maintained aggregate state is a result-cache tenant; the matview
    # module exemption in conftest keeps the cache armed, this pins the
    # budget so the suite is deterministic under env drift
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    yield


def _mk(n=60, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": rng.choice(["a", "b", "c", None], n).astype(object),
        "x": np.round(rng.random(n) * 10, 3),
        "y": rng.integers(0, 100, n),
    })


AGG_SQL = ("SELECT k, SUM(x) AS sx, COUNT(*) AS n, COUNT(y) AS ny, "
           "AVG(y) AS ay, MIN(x) AS mn, MAX(x) AS mx FROM t GROUP BY k")


def _oracle(frame):
    g = frame.groupby("k", dropna=False)
    out = pd.DataFrame({
        "sx": g["x"].sum(), "n": g.size(), "ny": g["y"].count(),
        "ay": g["y"].mean(), "mn": g["x"].min(), "mx": g["x"].max(),
    }).reset_index()
    return out


def _counters(*names):
    snap = _tel.REGISTRY.counters()
    return {n: snap.get(n, 0) for n in names}


def test_oracle_parity_multi_append_and_overwrite():
    c = Context()
    base = _mk()
    c.create_table("t", base)
    c.sql(f"CREATE MATERIALIZED VIEW v AS {AGG_SQL}")
    before = _counters("mv_refresh_incremental", "mv_refresh_full",
                       "mv_serves")
    assert_eq(c.sql("SELECT * FROM v"), _oracle(base),
              check_row_order=False)
    for i in range(3):  # >= 3 successive appends (acceptance criteria)
        add = _mk(9, seed=10 + i)
        c.append_rows("t", add)
        base = pd.concat([base, add], ignore_index=True)
        assert_eq(c.sql("SELECT * FROM v"), _oracle(base),
                  check_row_order=False)
    after = _counters("mv_refresh_incremental", "mv_refresh_full",
                      "mv_serves")
    # every one of the three appends was maintained, never recomputed
    assert after["mv_refresh_incremental"] - \
        before["mv_refresh_incremental"] == 3
    assert after["mv_refresh_full"] == before["mv_refresh_full"]
    assert after["mv_serves"] - before["mv_serves"] == 4

    # one overwrite (acceptance criteria): full recompute, never stale
    base = base[base.k != "b"].reset_index(drop=True)
    c.create_table("t", base)
    assert_eq(c.sql("SELECT * FROM v"), _oracle(base),
              check_row_order=False)
    final = _counters("mv_refresh_incremental", "mv_refresh_full")
    assert final["mv_refresh_full"] == after["mv_refresh_full"] + 1
    assert final["mv_refresh_incremental"] == \
        after["mv_refresh_incremental"]


def test_stale_view_never_served_after_overwrite():
    """Satellite regression: an overwrite between serves must drop the
    maintained state even when an append's delta is still pending."""
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    c.append_rows("t", [("a", 10.0)])  # pending delta, not yet applied
    c.create_table("t", pd.DataFrame({"k": ["z"], "x": [9.0]}))
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert list(got["k"]) == ["z"] and float(got["s"][0]) == 9.0


def test_insert_into_values_and_select():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a"], "x": [1.0]}))
    c.create_table("src", pd.DataFrame({"k": ["b", "c"], "x": [2.0, 3.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    c.sql("INSERT INTO t VALUES ('d', 4.0), ('e', NULL)")
    c.sql("INSERT INTO t SELECT * FROM src")
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert float(got["s"][0]) == pytest.approx(10.0)
    assert _tel.REGISTRY.get("mv_refresh_incremental") >= 1


def test_projection_pipeline_view_appends():
    c = Context()
    base = _mk(40)
    c.create_table("t", base)
    c.sql("CREATE MATERIALIZED VIEW vp AS SELECT k, x * 2 AS x2 FROM t "
          "WHERE y >= 50")
    for i in range(2):
        add = _mk(11, seed=33 + i)
        c.append_rows("t", add)
        base = pd.concat([base, add], ignore_index=True)
        exp = base[base.y >= 50][["k"]].assign(x2=base[base.y >= 50].x * 2)
        assert_eq(c.sql("SELECT * FROM vp"), exp.reset_index(drop=True),
                  check_row_order=False)


def test_refresh_after_drop_and_recreate():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a"], "x": [1.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    c.sql("DROP MATERIALIZED VIEW v")
    with pytest.raises(Exception):
        c.sql("SELECT * FROM v")
    # recreate over a mutated base: fresh full build, fresh watermarks
    c.append_rows("t", [("b", 5.0)])
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    c.sql("REFRESH MATERIALIZED VIEW v")  # fresh -> no-op
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert float(got["s"][0]) == 6.0


def test_explicit_refresh_applies_pending_deltas():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a"], "x": [1.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    before = _tel.REGISTRY.get("mv_refresh_incremental")
    c.append_rows("t", [("b", 2.0)])
    c.sql("REFRESH MATERIALIZED VIEW v")
    assert _tel.REGISTRY.get("mv_refresh_incremental") == before + 1
    # the serve right after is fresh: no second refresh
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert float(got["s"][0]) == 3.0
    assert _tel.REGISTRY.get("mv_refresh_incremental") == before + 1


def test_drop_table_on_matview_cleans_registry():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a"], "x": [1.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    c.sql("DROP TABLE v")
    assert ("root", "v") not in c._matview_registry.views
    # the base no longer has a dependent: appends record nothing
    c.append_rows("t", [("b", 2.0)])
    assert ("root", "t") not in c._matview_registry.deltas


def test_non_maintainable_view_full_recompute_reason_surfaced():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "a", "b"],
                                      "x": [1.0, 1.0, 2.0]}))
    # COUNT(DISTINCT) mixed with another aggregate exceeds the refcounted
    # value state (ISSUE 20 maintains only the single-agg form)
    c.sql("CREATE MATERIALIZED VIEW vd AS SELECT COUNT(DISTINCT k) AS n, "
          "SUM(x) AS s FROM t")
    full0 = _tel.REGISTRY.get("mv_refresh_full")
    c.append_rows("t", [("c", 3.0)])
    got = c.sql("SELECT * FROM vd", return_futures=False)
    assert int(got["n"][0]) == 3 and float(got["s"][0]) == 7.0
    assert _tel.REGISTRY.get("mv_refresh_full") == full0 + 1
    rows = c.sql("SELECT maintainable, reason FROM system.matviews "
                 "WHERE name = 'vd'", return_futures=False)
    assert rows["maintainable"][0] == "full"
    assert "DISTINCT" in rows["reason"][0]


def test_fault_mv_refresh_falls_back_to_full_recompute():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    c.append_rows("t", [("a", 10.0)])
    full0 = _tel.REGISTRY.get("mv_refresh_full")
    fault0 = _tel.REGISTRY.get("fault_mv_refresh")
    with faults.inject("mv_refresh:1"):
        got = c.sql("SELECT * FROM v", return_futures=False)
    got = got.sort_values("k").reset_index(drop=True)
    assert list(got["s"]) == [11.0, 2.0]  # wrong-never
    assert _tel.REGISTRY.get("fault_mv_refresh") == fault0 + 1
    assert _tel.REGISTRY.get("mv_refresh_full") == full0 + 1


def test_state_eviction_downgrades_to_full(monkeypatch):
    from dask_sql_tpu.runtime import result_cache as _rc
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    _rc.get_cache().clear()  # stands in for ledger-pressure eviction
    full0 = _tel.REGISTRY.get("mv_refresh_full")
    c.append_rows("t", [("a", 10.0)])
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert sorted(got["s"]) == [2.0, 11.0]
    assert _tel.REGISTRY.get("mv_refresh_full") == full0 + 1


def test_kill_switch_baseline(monkeypatch):
    """DSQL_MV=0 restores pre-subsystem behavior: base queries answer
    identically, MV DDL raises, appends still tombstone correctly."""
    monkeypatch.setenv("DSQL_MV", "0")
    c = Context()
    base = _mk(30)
    c.create_table("t", base)
    with pytest.raises(UserError):
        c.sql(f"CREATE MATERIALIZED VIEW v AS {AGG_SQL}")
    mv0 = _counters("mv_serves", "mv_refresh_incremental",
                    "mv_refresh_full", "mv_deltas_recorded")
    assert_eq(c.sql(AGG_SQL), _oracle(base), check_row_order=False)
    c.append_rows("t", [("a", 1.0, 1)])
    base = pd.concat([base, pd.DataFrame(
        {"k": ["a"], "x": [1.0], "y": [1]})], ignore_index=True)
    assert_eq(c.sql(AGG_SQL), _oracle(base), check_row_order=False)
    assert _counters("mv_serves", "mv_refresh_incremental",
                     "mv_refresh_full", "mv_deltas_recorded") == mv0


def test_disable_after_create_serves_without_refresh(monkeypatch):
    """Flipping DSQL_MV=0 with live views: serves pass through untouched
    (the entry as materialized), no maintenance runs."""
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a"], "x": [1.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    monkeypatch.setenv("DSQL_MV", "0")
    serves0 = _tel.REGISTRY.get("mv_serves")
    c.append_rows("t", [("b", 5.0)])
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert float(got["s"][0]) == 1.0  # frozen at creation, by contract
    assert _tel.REGISTRY.get("mv_serves") == serves0


def test_system_matviews_counters_reconcile():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    c.append_rows("t", [("a", 1.0)])
    c.sql("SELECT * FROM v")
    c.sql("SELECT COUNT(*) AS n FROM v")
    rows = c.sql("SELECT * FROM system.matviews", return_futures=False)
    assert len(rows) == 1
    r = rows.iloc[0]
    assert r["name"] == "v" and r["base_tables"] == "root.t"
    assert r["maintainable"] == "incremental:agg"
    assert int(r["refresh_incremental"]) == 1
    assert int(r["refresh_full"]) == 1  # the initial materialization
    assert int(r["serves"]) == 2
    assert int(r["pending_deltas"]) == 0


def test_view_candidates_ranked_by_hits_times_cost(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_HISTORY_FILE", str(tmp_path / "hist.jsonl"))
    # result-cache hits short-circuit execution and thus history
    # recording; the hit counter needs every run to land in the recorder
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "0")
    c = Context()
    c.create_table("t", _mk(50))
    hot = "SELECT k, SUM(x) AS s FROM t GROUP BY k"
    for _ in range(4):
        c.sql(hot)
    c.sql("SELECT MAX(y) AS m FROM t")
    rows = c.sql("SELECT * FROM system.view_candidates",
                 return_futures=False)
    assert len(rows) >= 2
    # the hot fingerprint ranks first (score = hits x ewma cost)
    assert int(rows["hits"][0]) == 4
    assert rows["score"][0] >= rows["score"].max() - 1e-9
    assert "GROUP BY" in rows["example_sql"][0]
    assert not bool(rows["materialized"][0])
    # materializing it flips the flag
    c.sql(f"CREATE MATERIALIZED VIEW hotv AS {hot}")
    rows = c.sql("SELECT * FROM system.view_candidates",
                 return_futures=False)
    assert bool(rows["materialized"][0])


def test_view_candidates_empty_without_recorder():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1]}))
    rows = c.sql("SELECT * FROM system.view_candidates",
                 return_futures=False)
    assert len(rows) == 0


def test_matview_in_secondary_schema():
    c = Context()
    c.create_schema("s2")
    c.create_table("t", pd.DataFrame({"x": [1.0, 2.0]}), schema_name="s2")
    c.sql("CREATE MATERIALIZED VIEW s2.v AS SELECT SUM(x) AS s FROM s2.t")
    c.append_rows("t", [(3.0,)], schema_name="s2")
    got = c.sql("SELECT * FROM s2.v", return_futures=False)
    assert float(got["s"][0]) == 6.0
    c.sql("DROP MATERIALIZED VIEW s2.v")


def test_view_over_view_chain_stays_fresh():
    c = Context()
    c.create_table("t", pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]}))
    c.sql("CREATE MATERIALIZED VIEW v1 AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    c.sql("CREATE MATERIALIZED VIEW v2 AS SELECT MAX(s) AS m FROM v1")
    c.append_rows("t", [("a", 10.0)])
    got = c.sql("SELECT * FROM v2", return_futures=False)
    assert float(got["m"][0]) == 11.0
