"""Schema DDL tests (reference: tests/integration/test_schema.py)."""
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_create_and_use_schema(c, df_simple):
    c.sql("CREATE SCHEMA other")
    c.sql("USE SCHEMA other")
    assert c.schema_name == "other"
    c.create_table("other_table", df_simple)
    assert_eq(c.sql("SELECT * FROM other_table"), df_simple)
    # root tables still reachable by qualification
    assert_eq(c.sql("SELECT * FROM root.df_simple"), df_simple)
    c.sql("USE SCHEMA root")
    assert_eq(c.sql("SELECT * FROM other.other_table"), df_simple)


def test_drop_schema(c):
    c.sql("CREATE SCHEMA to_drop")
    c.sql("DROP SCHEMA to_drop")
    assert "to_drop" not in c.schema
    with pytest.raises(RuntimeError):
        c.sql("DROP SCHEMA to_drop")
    c.sql("DROP SCHEMA IF EXISTS to_drop")


def test_schema_already_exists(c):
    c.sql("CREATE SCHEMA dup")
    with pytest.raises(RuntimeError):
        c.sql("CREATE SCHEMA dup")
    c.sql("CREATE SCHEMA IF NOT EXISTS dup")
    c.sql("CREATE OR REPLACE SCHEMA dup")


def test_use_unknown_schema(c):
    with pytest.raises(RuntimeError):
        c.sql("USE SCHEMA unknown")


def test_drop_default_schema_fails(c):
    with pytest.raises(RuntimeError):
        c.sql("DROP SCHEMA root")
