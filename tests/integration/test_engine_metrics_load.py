"""GET /v1/engine and GET /metrics under concurrent mixed-priority load
(ISSUE 15 satellite): the snapshot never throws mid-mutation, counters
stay monotonic poll-over-poll, and gauges stay inside the configured
scheduler bounds while 2-slot admission churns 12 client threads."""
import json
import re
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest


@pytest.fixture()
def server(monkeypatch):
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "16")
    monkeypatch.setenv("DSQL_EVENTS", "1")
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.runtime import events as ev
    from dask_sql_tpu.server.app import run_server

    ev._reset_for_tests()
    context = Context()
    context.create_table("t", {"a": np.arange(64, dtype=np.int64),
                               "g": np.arange(64, dtype=np.int64) % 8})
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()
    ev._reset_for_tests()


def _get_raw(url, headers=None):
    req = urllib.request.Request(url, headers=headers or {})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.read()


def _scrape(text):
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        m = re.match(r"^(\w+)(?:\{[^}]*\})?\s+([-\d.e+]+)$", line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def test_snapshots_survive_concurrent_mixed_priority_load(server):
    base = server
    queries = ["SELECT SUM(a) AS s FROM t",
               "SELECT g, COUNT(*) AS n FROM t GROUP BY g",
               "SELECT MAX(a) AS m FROM t WHERE a > 3"]
    priorities = ["interactive", "batch", "background"]
    errors = []
    done = threading.Event()

    def client(i):
        try:
            for j in range(4):
                body = queries[(i + j) % 3].encode()
                req = urllib.request.Request(
                    f"{base}/v1/statement", data=body,
                    headers={"X-DSQL-Priority": priorities[i % 3]},
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=60) as r:
                        payload = json.loads(r.read())
                except urllib.error.HTTPError as e:
                    # 429 under a full queue is a legal verdict here
                    assert e.code in (429, 503), e.code
                    continue
                while "nextUri" in payload:
                    with urllib.request.urlopen(payload["nextUri"],
                                                timeout=60) as r:
                        payload = json.loads(r.read())
                assert "data" in payload or "error" in payload
        except Exception as e:                       # pragma: no cover
            errors.append(e)

    def poller():
        try:
            _poll_loop()
        except Exception as e:
            errors.append(e)

    def _poll_loop():
        """Hammer both read surfaces while the load runs; every
        response must parse and respect the invariants."""
        last_queries = -1.0
        last_published = -1.0
        while not done.is_set():
            snap = json.loads(_get_raw(f"{base}/v1/engine"))
            sched = snap["scheduler"]
            assert sched["enabled"] is True
            assert 0 <= sched["running"] <= 2
            assert sched["queueDepth"] <= 2 + 16
            assert snap["slo"]["enabled"] is True
            for row in snap["slo"]["classes"]:
                assert 0.0 <= row["attainment"] <= 1.0
                assert row["burn_fast"] >= 0.0
            mets = _scrape(_get_raw(f"{base}/metrics").decode())
            q = mets.get("dsql_server_queries_total", 0.0)
            assert q >= last_queries          # counters only go up
            last_queries = q
            p = mets.get("dsql_events_published_total", 0.0)
            assert p >= last_published
            last_published = p
            g = mets.get("dsql_sched_queue_depth")
            if g is not None:
                assert 0 <= g <= 2 + 16
            for cls in ("interactive", "batch", "background"):
                att = mets.get(f"dsql_slo_attainment_{cls}")
                if att is not None:
                    assert 0.0 <= att <= 1.0

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    pollers = [threading.Thread(target=poller) for _ in range(2)]
    for t in pollers + threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    done.set()
    for t in pollers:
        t.join(timeout=60)
    assert not errors, errors
    assert not any(t.is_alive() for t in threads + pollers)

    # quiesced: the final snapshot agrees with itself
    snap = json.loads(_get_raw(f"{base}/v1/engine"))
    assert snap["scheduler"]["running"] == 0
    total = sum(r["total"] for r in snap["slo"]["classes"])
    assert total >= 1
