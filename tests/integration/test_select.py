"""SELECT / projection tests (reference: tests/integration/test_select.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_select_all(c, df):
    assert_eq(c.sql("SELECT * FROM df"), df)


def test_select_column(c, df):
    assert_eq(c.sql("SELECT a FROM df"), df[["a"]])


def test_select_different_types(c):
    expected = pd.DataFrame({
        "date": pd.to_datetime(["2022-01-21 17:34", "2022-01-21", "2021-11-23", None],
                               format="mixed"),
        "string": ["this is a test", "another test", "äölüć", ""],
        "integer": [1, 2, -4, 5],
        "float": [-1.1, np.nan, 2.3, -4.5],
    })
    c.create_table("df2", expected)
    assert_eq(c.sql("SELECT * FROM df2"), expected)


def test_select_expr(c, df):
    result = c.sql("SELECT a + 1 AS a, b AS bla, a - 1 FROM df").to_pandas()
    expected = pd.DataFrame({"a": df["a"] + 1, "bla": df["b"], "a - 1": df["a"] - 1})
    expected.columns = ["a", "bla", "EXPR$2"]
    assert_eq(result, expected)


def test_select_of_select(c, df):
    result = c.sql(
        """
        SELECT 2*c AS e, d - 1 AS f
        FROM (SELECT a - 1 AS c, 2*b AS d FROM df) AS "inner"
        """
    )
    expected = pd.DataFrame({"e": 2 * (df["a"] - 1), "f": 2 * df["b"] - 1})
    assert_eq(result, expected)


def test_select_of_select_with_casing(c, df):
    result = c.sql(
        """
        SELECT AAA, aaa, aAa
        FROM (SELECT a - 1 AS aAa, 2*b AS aaa, a + b AS AAA FROM df) AS "inner"
        """
    )
    expected = pd.DataFrame(
        {"AAA": df["a"] + df["b"], "aaa": 2 * df["b"], "aAa": df["a"] - 1}
    )
    assert_eq(result, expected)


def test_wrong_input(c):
    from dask_sql_tpu.utils import ParsingException

    with pytest.raises(ParsingException):
        c.sql("SELECT x FROM df")
    with pytest.raises(ParsingException):
        c.sql("SELECT x FROM unknown_table")


def test_timezones(c, datetime_table):
    result = c.sql("SELECT * FROM datetime_table")
    expected = datetime_table.copy()
    # tz-aware columns are normalized to naive UTC on device
    expected["timezone"] = expected["timezone"].dt.tz_convert("UTC").dt.tz_localize(None)
    expected["utc_timezone"] = expected["utc_timezone"].dt.tz_localize(None)
    assert_eq(result, expected)


def test_select_from_values(c):
    result = c.sql("VALUES (1, 'a'), (2, 'b')")
    expected = pd.DataFrame({"EXPR$0": [1, 2], "EXPR$1": ["a", "b"]})
    assert_eq(result, expected)


def test_literals(c):
    result = c.sql(
        """
        SELECT 'a string äö' AS "S",
               4.4 AS "F",
               -4564347464 AS "I",
               TIME '08:08:00.091' AS "T",
               TIMESTAMP '2022-04-06 17:33:21' AS "DT",
               DATE '1991-06-02' AS "D",
               TRUE AS "B"
        """
    ).to_pandas()
    assert result["S"][0] == "a string äö"
    assert result["F"][0] == 4.4
    assert result["I"][0] == -4564347464
    assert result["DT"][0] == pd.Timestamp("2022-04-06 17:33:21")
    assert result["D"][0] == pd.Timestamp("1991-06-02")
    assert bool(result["B"][0]) is True


def test_multiple_statements(c, df):
    result = c.sql("SELECT a FROM df; SELECT b FROM df")
    assert_eq(result, df[["b"]])


def test_null_literal(c):
    result = c.sql("SELECT NULL AS n, 1 AS o").to_pandas()
    assert result["n"].isna().all()
