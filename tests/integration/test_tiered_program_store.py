"""Integration: tiered eager-first execution + the persistent program store.

Covers the acceptance surface of ISSUE 7:
- a cold query answers on the eager tier WITHOUT blocking on stage
  compilation, oracle-correct, while the programs build in the background;
  the next arrival of the same plan runs compiled;
- a fresh process (simulated by clearing every in-memory program cache,
  and proven for real with a subprocess) serves a previously-seen query
  from the persistent store with ZERO XLA stage compiles;
- store safety: corrupt entries and fingerprint mismatches fall back to a
  normal compile (never a crash), and DDL can never surface stale data
  (programs are data-independent — fresh inputs flow through them).
"""
import os
import pickle
import subprocess
import sys
import time

import pandas as pd
import pytest

import jax

from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import program_store as ps
from dask_sql_tpu.runtime import telemetry as tel


def _deltas(c0):
    now = tel.REGISTRY.counters()
    return {k: v - c0.get(k, 0) for k, v in now.items() if v != c0.get(k, 0)}


def _forget_programs():
    """Drop every in-memory trace of compiled programs — the same state a
    fresh process starts from (the subprocess test proves the real thing)."""
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()
    with compiled._tier_lock:
        compiled._tier_done.clear()
        compiled._tier_inflight.clear()
    jax.clear_caches()


@pytest.fixture()
def pstore(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_PROGRAM_STORE", str(tmp_path / "programs"))
    monkeypatch.setenv("DSQL_TIERED", "0")
    _forget_programs()
    yield ps.get_store()
    _forget_programs()


QUERY = ("SELECT a, SUM(b) AS sb, COUNT(*) AS n FROM df "
         "GROUP BY a ORDER BY a")


def _eager_oracle(c, query):
    prev = os.environ.get("DSQL_COMPILE")
    os.environ["DSQL_COMPILE"] = "0"
    try:
        return c.sql(query, return_futures=False)
    finally:
        if prev is None:
            del os.environ["DSQL_COMPILE"]
        else:
            os.environ["DSQL_COMPILE"] = prev


# ---------------------------------------------------------------------------
# persistent store
# ---------------------------------------------------------------------------

def test_fresh_load_executes_with_zero_compiles(c, pstore):
    c0 = tel.REGISTRY.counters()
    cold = c.sql(QUERY, return_futures=False)
    d1 = _deltas(c0)
    assert d1.get("compiles", 0) >= 1
    assert d1.get("program_store_stores", 0) >= 1

    _forget_programs()
    c1 = tel.REGISTRY.counters()
    warm = c.sql(QUERY, return_futures=False)
    d2 = _deltas(c1)
    assert d2.get("compiles", 0) == 0, d2
    assert d2.get("program_store_hits", 0) >= 1, d2
    pd.testing.assert_frame_equal(cold, warm)
    pd.testing.assert_frame_equal(warm, _eager_oracle(c, QUERY),
                                  check_dtype=False)


def test_store_caps_survive_fresh_process(c, pstore):
    # long_table overflows the default group cap? No — 3 groups.  Force an
    # escalation instead via a tiny learned cap, then prove the RE-stored
    # program (escalated caps) is what a fresh process loads: no
    # recompile, no _NeedsRecompile loop.
    cold = c.sql(QUERY, return_futures=False)
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    warm = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("recompiles", 0) == 0 and d.get("compiles", 0) == 0, d
    pd.testing.assert_frame_equal(cold, warm)


def test_corrupt_entry_falls_back_to_compile(c, pstore):
    c.sql(QUERY, return_futures=False)
    store_dir = pstore.path()
    progs = [f for f in os.listdir(store_dir) if f.endswith(".prog")]
    assert progs
    for f in progs:
        with open(os.path.join(store_dir, f), "wb") as fh:
            fh.write(b"\x80corrupt")
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    out = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("program_store_errors", 0) >= 1, d
    assert d.get("compiles", 0) >= 1, d  # recompiled, didn't crash
    pd.testing.assert_frame_equal(out, _eager_oracle(c, QUERY),
                                  check_dtype=False)


def test_fingerprint_mismatch_falls_back_to_compile(c, pstore):
    c.sql(QUERY, return_futures=False)
    store_dir = pstore.path()
    for f in os.listdir(store_dir):
        if not f.endswith(".prog"):
            continue
        path = os.path.join(store_dir, f)
        with open(path, "rb") as fh:
            raw = pickle.load(fh)
        raw["fingerprint"] = dict(raw["fingerprint"], jax="0.0.0")
        with open(path, "wb") as fh:
            pickle.dump(raw, fh)
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    out = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("program_store_rejects", 0) >= 1, d
    assert d.get("compiles", 0) >= 1, d
    pd.testing.assert_frame_equal(out, _eager_oracle(c, QUERY),
                                  check_dtype=False)


def test_ddl_same_layout_serves_fresh_data(c, pstore, df):
    """A stored program must never pin stale DATA: after DROP + re-create
    with same-layout different contents, the loaded program computes the
    NEW answer (inputs are runtime arguments, not baked constants)."""
    old = c.sql(QUERY, return_futures=False)
    df2 = df.copy()
    df2["b"] = df2["b"] * 3.0
    c.drop_table("df")
    c.create_table("df", df2)
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    new = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("compiles", 0) == 0, d  # layout unchanged: store hit
    assert d.get("program_store_hits", 0) >= 1
    assert not new["sb"].equals(old["sb"])  # fresh data, fresh answer
    pd.testing.assert_frame_equal(new, _eager_oracle(c, QUERY),
                                  check_dtype=False)


def test_ddl_layout_change_misses_cleanly(c, pstore, df):
    """A changed plan shape/layout must address a DIFFERENT store entry —
    the old program can never be served for the new shape."""
    c.sql(QUERY, return_futures=False)
    df3 = df.copy()
    df3["a"] = df3["a"].astype("int64")  # dtype change reshapes the layout
    c.drop_table("df")
    c.create_table("df", df3)
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    out = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("program_store_hits", 0) == 0, d
    assert d.get("compiles", 0) >= 1
    pd.testing.assert_frame_equal(out, _eager_oracle(c, QUERY),
                                  check_dtype=False)


def test_stage_graph_programs_persist(c, pstore, monkeypatch):
    """A multi-stage plan persists one entry per stage program and a fresh
    process replays ALL of them with zero compiles."""
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    query = ("SELECT u1.user_id, SUM(u2.c) AS s FROM user_table_1 u1 "
             "JOIN user_table_2 u2 ON u1.user_id = u2.user_id "
             "GROUP BY u1.user_id ORDER BY u1.user_id")
    c0 = tel.REGISTRY.counters()
    cold = c.sql(query, return_futures=False)
    d1 = _deltas(c0)
    assert d1.get("stage_graphs", 0) >= 1
    assert d1.get("program_store_stores", 0) >= 2  # one per stage program

    _forget_programs()
    c1 = tel.REGISTRY.counters()
    warm = c.sql(query, return_futures=False)
    d2 = _deltas(c1)
    assert d2.get("compiles", 0) == 0, d2
    assert d2.get("program_store_hits", 0) >= 2, d2
    pd.testing.assert_frame_equal(cold, warm)


# ---------------------------------------------------------------------------
# tiered execution
# ---------------------------------------------------------------------------

@pytest.fixture()
def tiered(monkeypatch):
    monkeypatch.setenv("DSQL_TIERED", "1")
    monkeypatch.delenv("DSQL_PROGRAM_STORE", raising=False)
    _forget_programs()
    yield
    _forget_programs()


def _wait_background(c0, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        done = tel.REGISTRY.get("background_compiles_done") \
            - c0.get("background_compiles_done", 0)
        err = tel.REGISTRY.get("background_compile_errors") \
            - c0.get("background_compile_errors", 0)
        if done + err >= 1:
            return done, err
        time.sleep(0.05)
    return 0, 0


def test_tiered_first_arrival_serves_eager_then_compiled(c, tiered,
                                                         monkeypatch):
    # prime the eager executor's op programs (cleared per module) so the
    # eager-tier answer below is comfortably faster than the slowed build
    _eager_oracle(c, QUERY)
    real_build = compiled._build

    def slow_build(*a, **k):
        time.sleep(4.0)
        return real_build(*a, **k)

    monkeypatch.setattr(compiled, "_build", slow_build)
    c0 = tel.REGISTRY.counters()
    first = c.sql(QUERY, return_futures=False)
    d1 = _deltas(c0)
    # answered on the eager tier, with the compile NOT yet landed: the
    # query did not block on the (slowed) build
    assert d1.get("served_eager_while_compiling", 0) == 1, d1
    assert d1.get("compiles", 0) == 0, d1
    assert c.last_report.tier == "eager-compiling"
    pd.testing.assert_frame_equal(first, _eager_oracle(c, QUERY),
                                  check_dtype=False)

    done, err = _wait_background(c0)
    assert done == 1 and err == 0, (done, err)
    c1 = tel.REGISTRY.counters()
    second = c.sql(QUERY, return_futures=False)
    d2 = _deltas(c1)
    assert d2.get("served_eager_while_compiling", 0) == 0, d2
    assert d2.get("hits", 0) >= 1, d2  # ran the compiled program
    assert c.last_report.tier == "compiled"
    pd.testing.assert_frame_equal(first, second, check_dtype=False)


def test_tiered_concurrent_arrivals_stay_eager_until_ready(c, tiered,
                                                           monkeypatch):
    _eager_oracle(c, QUERY)  # prime eager op programs (see above)
    real_build = compiled._build
    monkeypatch.setattr(
        compiled, "_build",
        lambda *a, **k: (time.sleep(3.0), real_build(*a, **k))[1])
    c0 = tel.REGISTRY.counters()
    r1 = c.sql(QUERY, return_futures=False)
    r2 = c.sql(QUERY, return_futures=False)  # bg compile still in flight
    d = _deltas(c0)
    assert d.get("served_eager_while_compiling", 0) == 2, d
    # one background compile for the plan, not one per arrival
    _wait_background(c0)
    assert tel.REGISTRY.get("background_compiles_done") \
        - c0.get("background_compiles_done", 0) == 1
    pd.testing.assert_frame_equal(r1, r2)


def test_tiered_off_compiles_synchronously(c, monkeypatch):
    monkeypatch.setenv("DSQL_TIERED", "0")
    _forget_programs()
    c0 = tel.REGISTRY.counters()
    c.sql(QUERY, return_futures=False)
    d = _deltas(c0)
    assert d.get("served_eager_while_compiling", 0) == 0
    assert d.get("compiles", 0) >= 1
    assert c.last_report.tier == "compiled"


def test_tiered_respects_eager_fallback_off(c, tiered, monkeypatch):
    # the degradation ladder forbids the eager tier: compiles must be
    # synchronous again (no tier to serve from)
    monkeypatch.setenv("DSQL_EAGER_FALLBACK", "0")
    c0 = tel.REGISTRY.counters()
    c.sql(QUERY, return_futures=False)
    d = _deltas(c0)
    assert d.get("served_eager_while_compiling", 0) == 0, d
    assert d.get("compiles", 0) >= 1


def test_tiered_unsupported_plans_never_spawn_background(c, tiered):
    # RAND() is in the deny-set: permanently eager, no tier churn
    c0 = tel.REGISTRY.counters()
    c.sql("SELECT a, RAND(0) AS r FROM df_simple", return_futures=False)
    d = _deltas(c0)
    assert d.get("served_eager_while_compiling", 0) == 0, d
    assert d.get("background_compiles_done", 0) == 0


def test_tiered_with_store_serves_warm_without_eager_tier(c, tiered,
                                                          tmp_path,
                                                          monkeypatch):
    """Tier decision consults the persistent store: a fresh 'process' with
    a populated store runs compiled immediately — no eager tier, no
    background work, zero compiles."""
    monkeypatch.setenv("DSQL_PROGRAM_STORE", str(tmp_path / "programs"))
    c0 = tel.REGISTRY.counters()
    c.sql(QUERY, return_futures=False)
    _wait_background(c0)
    assert tel.REGISTRY.counters().get("program_store_stores", 0) \
        - c0.get("program_store_stores", 0) >= 1
    _forget_programs()
    c1 = tel.REGISTRY.counters()
    out = c.sql(QUERY, return_futures=False)
    d = _deltas(c1)
    assert d.get("served_eager_while_compiling", 0) == 0, d
    assert d.get("compiles", 0) == 0, d
    assert d.get("program_store_hits", 0) >= 1, d
    assert c.last_report.tier == "compiled"
    pd.testing.assert_frame_equal(out, _eager_oracle(c, QUERY),
                                  check_dtype=False)


# ---------------------------------------------------------------------------
# the real cross-process proof (a true fresh interpreter)
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["DSQL_RESULT_CACHE_MB"] = "0"
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
os.environ["DSQL_TIERED"] = "0"
import pandas as pd
from dask_sql_tpu import Context
from dask_sql_tpu.runtime import telemetry as tel

data = pd.read_feather(sys.argv[1])
c = Context()
c.create_table("t", data)
q = ("SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t "
     "GROUP BY k ORDER BY k")
out = c.sql(q, return_futures=False)
snap = tel.REGISTRY.counters()
print(json.dumps({
    "result": out.to_dict("list"),
    "compiles": snap["compiles"],
    "program_store_hits": snap["program_store_hits"],
    "program_store_stores": snap["program_store_stores"],
}))
"""


@pytest.mark.slow  # two real interpreter launches; the tier-1 box runs the
# same proof in-process above, and scripts/warmstart_smoke.py gates the
# cross-process version in CI
def test_fresh_process_serves_warm(tmp_path):
    """Two real interpreters sharing only DSQL_PROGRAM_STORE: the second
    answers with zero XLA compiles and store hits == programs executed."""
    data_path = str(tmp_path / "t.feather")
    pd.DataFrame({"k": [1, 2, 1, 3, 2, 1] * 50,
                  "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0] * 50}
                 ).to_feather(data_path)
    env = dict(os.environ,
               DSQL_PROGRAM_STORE=str(tmp_path / "programs"),
               JAX_PLATFORMS="cpu")
    env.pop("DSQL_FAULT_INJECT", None)

    import json
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", _CHILD, data_path],
                           capture_output=True, text=True, env=env,
                           timeout=240)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    first, second = outs
    assert first["compiles"] >= 1
    assert first["program_store_stores"] >= 1
    assert second["compiles"] == 0, second
    assert second["program_store_hits"] >= 1, second
    assert second["result"] == first["result"]
