"""Integration tests for spooled result paging (server/app.py, ISSUE 17):
large results page through the SpillStore behind a REAL nextUri, pages
free as fetched, the reaper GCs abandoned results AND the historical
future_list leak, and the DSQL_RESULT_PAGE_ROWS=0 kill switch restores
the classic single-shot payload bit-for-bit."""
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import spill as spill_mod
from dask_sql_tpu.runtime import telemetry as tel

ROWS = 1050
PAGE = 100


@pytest.fixture()
def server(monkeypatch, tmp_path):
    monkeypatch.setenv("DSQL_RESULT_PAGE_ROWS", str(PAGE))
    monkeypatch.setenv("DSQL_RESULT_TTL_S", "60")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path))
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table("t", pd.DataFrame({
        "a": np.arange(ROWS, dtype=np.int64),
        "b": np.arange(ROWS, dtype=np.float64) * 2.0,
    }))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield srv, f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _post(base, sql, headers=None):
    req = urllib.request.Request(f"{base}/v1/statement", data=sql.encode(),
                                 method="POST", headers=headers or {})
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read()), dict(r.headers)


def _poll_until_done(base, payload, timeout=60):
    """Follow the classic status loop until the response stops pointing
    at /v1/status (done: either a final payload or a /v1/result link)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        uri = payload.get("nextUri")
        if uri is None or "/v1/result/" in uri or "data" in payload:
            return payload
        time.sleep(0.05)
        payload, _ = _get(uri)
    raise AssertionError("query did not finish in time")


def _collect_pages(payload):
    """Drain the nextUri chain; returns (rows, page_row_counts)."""
    rows, counts = [], []
    while True:
        data = payload.get("data")
        if data:
            rows.extend(data)
            counts.append(len(data))
        uri = payload.get("nextUri")
        if uri is None:
            return rows, counts
        payload, _ = _get(uri)


def test_large_result_pages_and_reassembles(server):
    srv, base = server
    payload = _poll_until_done(base, _post(base, "SELECT a, b FROM t "
                                                 "ORDER BY a"))
    # the finishing /v1/status response is page 0 + a REAL nextUri
    assert "/v1/result/" in payload["nextUri"]
    assert len(payload["data"]) == PAGE
    rows, counts = _collect_pages(payload)
    assert len(rows) == ROWS
    assert rows[0] == [0, 0.0]
    assert rows[-1] == [ROWS - 1, (ROWS - 1) * 2.0]
    # no single response carried more than one page of rows
    assert max(counts) <= PAGE
    # every page freed as fetched: nothing left in the store or registry
    assert spill_mod.get_store().stats()["runs"] == 0
    assert not srv.app_state.spools
    assert not srv.app_state.future_list
    assert not srv.app_state.query_info
    assert tel.REGISTRY.get("result_spooled") >= 1
    assert tel.REGISTRY.get("result_pages_served") >= len(counts)


def test_status_repoll_and_page_replay_semantics(server):
    srv, base = server
    payload = _poll_until_done(base, _post(base, "SELECT a FROM t"))
    uid = payload["id"]
    first = payload["nextUri"]
    # a /v1/status re-poll after page 0: FINISHED, columns, nextUri to
    # the lowest uncollected page, and NO data (rows travel once)
    repoll, _ = _get(f"{base}/v1/status/{uid}")
    assert repoll["stats"]["state"] == "FINISHED"
    assert repoll["columns"]
    assert "data" not in repoll
    assert repoll["nextUri"].endswith("/1")
    # page 1 can be re-fetched (network-retry) until page 2 is taken
    p1a, _ = _get(first)
    p1b, _ = _get(first)
    assert p1a["data"] == p1b["data"]
    p2, _ = _get(p1a["nextUri"])
    assert p2["data"]
    # now page 1 is freed: 410 Gone, typed
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(first)
    assert ei.value.code == 410
    # cancel mid-page drops the spool and frees every remaining page
    req = urllib.request.Request(f"{base}/v1/cancel/{uid}",
                                 method="DELETE")
    urllib.request.urlopen(req).close()
    assert spill_mod.get_store().stats()["runs"] == 0
    assert uid not in srv.app_state.spools


def test_reaper_collects_abandoned_spool_and_future(server, monkeypatch):
    srv, base = server
    state = srv.app_state
    # (1) a spooled result the client walks away from mid-pagination
    payload = _poll_until_done(base, _post(base, "SELECT a FROM t"))
    uid_spool = payload["id"]
    assert uid_spool in state.spools
    # (2) a finished query whose result is never collected — the
    # historical future_list/query_info/seats leak
    submitted = _post(base, "SELECT COUNT(*) AS n FROM t")
    uid_leak = submitted["id"]
    deadline = time.time() + 30
    while time.time() < deadline:
        fut = state.future_list.get(uid_leak)
        if fut is not None and fut.done():
            break
        time.sleep(0.05)
    assert state.future_list[uid_leak].done()
    reaped0 = tel.REGISTRY.get("result_reaped")
    # TTL=0 disables reaping entirely
    monkeypatch.setenv("DSQL_RESULT_TTL_S", "0")
    assert state.reap_once(now=time.monotonic() + 10_000) == 0
    # a tick far past the TTL reaps both
    monkeypatch.setenv("DSQL_RESULT_TTL_S", "60")
    n = state.reap_once(now=time.monotonic() + 120)
    assert n >= 2
    assert uid_spool not in state.spools
    assert uid_leak not in state.future_list
    assert uid_leak not in state.query_info
    assert uid_leak not in state.seats
    assert spill_mod.get_store().stats()["runs"] == 0
    assert tel.REGISTRY.get("result_reaped") - reaped0 >= 2
    # the reaped entries no longer occupy /v1/engine
    eng, _ = _get(f"{base}/v1/engine")
    assert eng["serverQueries"] == []
    # a reaped result id answers 404, typed
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/v1/status/{uid_leak}")
    assert ei.value.code == 404


def test_result_spool_fault_degrades_to_unpaged(server):
    _, base = server
    with faults.inject("result_spool:1"):
        payload = _poll_until_done(base, _post(base, "SELECT a FROM t"))
        # the spool path faulted: the classic single-shot payload, whole
        # result inline, no /v1/result nextUri — degraded, never broken
        assert "nextUri" not in payload
        assert len(payload["data"]) == ROWS
    assert tel.REGISTRY.get("fault_result_spool") >= 1
    assert spill_mod.get_store().stats()["runs"] == 0


def test_small_results_never_spool(server):
    _, base = server
    payload = _poll_until_done(base, _post(base,
                                           "SELECT COUNT(*) AS n FROM t"))
    assert "nextUri" not in payload
    assert payload["data"] == [[ROWS]]
    assert spill_mod.get_store().stats()["runs"] == 0


def test_kill_switch_restores_single_shot_payload(server, monkeypatch):
    """DSQL_RESULT_PAGE_ROWS=0: the exact pre-paging payload — same keys,
    whole result inline, no spool, no /v1/result involvement."""
    _, base = server
    monkeypatch.setenv("DSQL_RESULT_PAGE_ROWS", "0")
    payload = _poll_until_done(base, _post(base, "SELECT a, b FROM t "
                                                 "ORDER BY a"))
    assert sorted(payload.keys()) == ["columns", "data", "id", "infoUri",
                                      "stats"]
    assert len(payload["data"]) == ROWS
    assert spill_mod.get_store().stats()["runs"] == 0
