"""The explicit SPMD stage executor (parallel/spmd.py) on the 8-device
CPU mesh: exchange / partial-aggregate primitives against pandas oracles,
end-to-end sharded queries with counters proving the sharded path served
them, pad-row and NULL-key invisibility, and cross-process program-store
round-trips of sharded stage programs.

The module name contains "spmd" so the conftest DSQL_MESH=0 pin does not
apply — these tests exercise the live multi-chip path on purpose.
"""
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest
from jax.sharding import PartitionSpec as P

from dask_sql_tpu import Context
from dask_sql_tpu.parallel import exchange as X
from dask_sql_tpu.parallel import partial_agg as PA
from dask_sql_tpu.parallel.mesh import ROW_AXIS, default_mesh, row_sharding
from dask_sql_tpu.runtime import telemetry as tel

try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map


@pytest.fixture(scope="module")
def mesh():
    m = default_mesh()
    if m.devices.size < 2:
        pytest.skip("needs a multi-device mesh")
    return m


def _shard(mesh, x):
    return jax.device_put(jnp.asarray(x), row_sharding(mesh))


def _spmd_deltas(c0):
    now = tel.REGISTRY.counters()
    return {k: v - c0.get(k, 0) for k, v in now.items()
            if k.startswith("spmd_") and v != c0.get(k, 0)}


# ---------------------------------------------------------------------------
# exchange primitives (inside shard_map, where the executor uses them)
# ---------------------------------------------------------------------------

def test_exchange_routes_by_code_and_preserves_rows(mesh):
    n_dev = int(mesh.devices.size)
    n = 16 * n_dev
    rng = np.random.RandomState(0)
    codes = rng.randint(0, 37, n).astype(np.int64)
    # every 5th row dead (code -1): must never resurface as a live row
    codes[::5] = -1
    payload = np.arange(n, dtype=np.float64)

    def body(c, p):
        c2, (p2,) = X.exchange(c, (p,), n_dev)
        return c2, p2

    wrapped = shard_map(body, mesh=mesh, in_specs=P(ROW_AXIS),
                        out_specs=P(ROW_AXIS))
    c2, p2 = wrapped(_shard(mesh, codes), _shard(mesh, payload))
    c2, p2 = np.asarray(c2), np.asarray(p2)

    live = c2 >= 0
    # routing: every live row landed on the device owning code % n_dev
    per_dev = np.split(c2, n_dev)
    for dev, chunk in enumerate(per_dev):
        chunk = chunk[chunk >= 0]
        assert (chunk % n_dev == dev).all()
    # conservation: the live (code, payload) multiset is exactly preserved
    want = sorted(zip(codes[codes >= 0], payload[codes >= 0]))
    got = sorted(zip(c2[live], p2[live]))
    assert got == want


def test_exchange_bytes_counts_payload_and_codes(mesh):
    n_dev = int(mesh.devices.size)
    codes = jnp.zeros(16, dtype=jnp.int64)   # one device's LOCAL shard
    pay = (jnp.zeros(16, dtype=jnp.float64),)
    # send-buffer volume across the whole mesh: each device scatters a
    # (n_dev, local) buffer per array -> size * itemsize * n_dev^2
    assert (X.exchange_bytes(codes, pay, n_dev)
            == 16 * 8 * 2 * n_dev * n_dev)


def test_shard_replicated_round_trip(mesh):
    n_dev = int(mesh.devices.size)
    k = n_dev + 3  # not divisible: forces padding

    def body(_):
        v = jnp.arange(k, dtype=jnp.float64) * 2.0
        out, kp = X.shard_replicated(v, n_dev)
        assert kp % n_dev == 0
        return out

    wrapped = shard_map(body, mesh=mesh, in_specs=P(ROW_AXIS),
                        out_specs=P(ROW_AXIS))
    out = np.asarray(wrapped(_shard(mesh, np.zeros(n_dev))))
    np.testing.assert_allclose(out[:k], np.arange(k) * 2.0)


# ---------------------------------------------------------------------------
# partial-aggregate combine trees
# ---------------------------------------------------------------------------

def test_global_sum_count_match_pandas_with_nulls(mesh):
    n_dev = int(mesh.devices.size)
    n = 8 * n_dev
    rng = np.random.RandomState(1)
    vals = rng.rand(n)
    ok = rng.rand(n) > 0.3  # dead rows: NULLs and pad rows alike

    def body(v, m):
        s, c = PA.global_sum(v, m, True)
        return X.shard_replicated(jnp.stack([s, c.astype(jnp.float64)]),
                                  n_dev)[0]

    wrapped = shard_map(body, mesh=mesh, in_specs=P(ROW_AXIS),
                        out_specs=P(ROW_AXIS))
    out = np.asarray(wrapped(_shard(mesh, vals), _shard(mesh, ok)))
    np.testing.assert_allclose(out[0], vals[ok].sum(), rtol=1e-12)
    assert int(out[1]) == int(ok.sum())


def test_global_minmax_ignores_dead_rows(mesh):
    n_dev = int(mesh.devices.size)
    n = 8 * n_dev
    rng = np.random.RandomState(2)
    vals = rng.randint(-50, 50, n).astype(np.int64)
    ok = np.ones(n, dtype=bool)
    ok[vals == vals.min()] = False  # kill the extremes: they must vanish
    ok[vals == vals.max()] = False

    def body(v, m):
        lo = PA.global_minmax(v, m, is_min=True, sharded=True)
        hi = PA.global_minmax(v, m, is_min=False, sharded=True)
        return X.shard_replicated(jnp.stack([lo, hi]), n_dev)[0]

    wrapped = shard_map(body, mesh=mesh, in_specs=P(ROW_AXIS),
                        out_specs=P(ROW_AXIS))
    out = np.asarray(wrapped(_shard(mesh, vals), _shard(mesh, ok)))
    assert int(out[0]) == int(vals[ok].min())
    assert int(out[1]) == int(vals[ok].max())


# ---------------------------------------------------------------------------
# end-to-end sharded SQL, counters as the proof of path
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def spmd_ctx(mesh):
    rng = np.random.RandomState(7)
    n = 8 * int(mesh.devices.size) + 5  # NOT divisible: pad rows exist
    fact = pd.DataFrame({
        "k": rng.randint(0, 20, n).astype(np.int64),
        "grp": rng.randint(0, 4, n).astype(np.int64),
        "v": np.round(rng.rand(n), 6),
    })
    # NULLs in both an aggregate input and a group key
    fact.loc[fact.index[::7], "v"] = np.nan
    gk = fact["grp"].astype("float64")
    gk[fact.index[::11]] = np.nan
    fact["gk"] = gk.astype("Int64")
    dim = pd.DataFrame({"k": np.arange(20, dtype=np.int64),
                        "w": np.round(np.arange(20) * 0.25, 6)})
    ctx = Context(mesh=mesh)
    ctx.create_table("fact", fact)
    ctx.create_table("dim", dim)
    return ctx, fact, dim


def test_global_agg_pad_rows_invisible(spmd_ctx):
    ctx, fact, _ = spmd_ctx
    c0 = tel.REGISTRY.counters()
    got = ctx.sql("SELECT COUNT(*) AS n, SUM(v) AS s, AVG(v) AS a "
                  "FROM fact", return_futures=False)
    d = _spmd_deltas(c0)
    assert d.get("spmd_queries", 0) == 1, d
    assert d.get("spmd_fallbacks", 0) == 0, d
    # COUNT(*) counts real rows only — pad rows from the non-divisible
    # shard layout must be invisible
    assert int(got["n"][0]) == len(fact)
    np.testing.assert_allclose(float(got["s"][0]), fact["v"].sum(),
                               rtol=1e-9)
    np.testing.assert_allclose(float(got["a"][0]),
                               fact["v"].mean(), rtol=1e-9)


def test_groupby_null_keys_match_pandas(spmd_ctx):
    ctx, fact, _ = spmd_ctx
    c0 = tel.REGISTRY.counters()
    got = ctx.sql("SELECT gk, COUNT(*) AS n, SUM(v) AS s FROM fact "
                  "GROUP BY gk ORDER BY gk", return_futures=False)
    d = _spmd_deltas(c0)
    assert d.get("spmd_queries", 0) == 1, d
    assert d.get("spmd_partial_aggs", 0) >= 1, d
    want = (fact.groupby("gk", dropna=False)
            .agg(n=("k", "size"), s=("v", "sum")).reset_index()
            .sort_values("gk", na_position="last").reset_index(drop=True))
    assert len(got) == len(want)
    nulls_got = got["gk"].isna().sum()
    assert nulls_got == want["gk"].isna().sum() == 1
    g = got.sort_values("gk", na_position="last").reset_index(drop=True)
    np.testing.assert_array_equal(g["n"].to_numpy(), want["n"].to_numpy())
    np.testing.assert_allclose(g["s"].to_numpy(dtype=float),
                               want["s"].to_numpy(dtype=float), rtol=1e-9)


def test_join_exchange_matches_pandas(spmd_ctx):
    ctx, fact, dim = spmd_ctx
    c0 = tel.REGISTRY.counters()
    got = ctx.sql("SELECT grp, SUM(v * w) AS rev FROM fact "
                  "JOIN dim ON fact.k = dim.k GROUP BY grp ORDER BY grp",
                  return_futures=False)
    d = _spmd_deltas(c0)
    assert d.get("spmd_queries", 0) == 1, d
    assert (d.get("spmd_broadcast_joins", 0)
            + d.get("spmd_exchange_joins", 0)) >= 1, d
    want = (fact.merge(dim, on="k").assign(rev=lambda x: x.v * x.w)
            .groupby("grp").agg(rev=("rev", "sum")).reset_index())
    np.testing.assert_allclose(got["rev"].to_numpy(dtype=float),
                               want["rev"].to_numpy(dtype=float), rtol=1e-9)


def test_forced_exchange_join(mesh, monkeypatch):
    # a zero broadcast cap forces the hash-partitioned all_to_all variant
    monkeypatch.setenv("DSQL_SPMD_BROADCAST_ROWS", "0")
    rng = np.random.RandomState(9)
    n = 16 * int(mesh.devices.size)
    a = pd.DataFrame({"k": rng.randint(0, 50, n).astype(np.int64),
                      "v": rng.rand(n)})
    b = pd.DataFrame({"k": np.arange(50, dtype=np.int64),
                      "w": np.arange(50) * 1.5})
    ctx = Context(mesh=mesh)
    ctx.create_table("a", a)
    ctx.create_table("b", b)
    c0 = tel.REGISTRY.counters()
    got = ctx.sql("SELECT SUM(v * w) AS s FROM a JOIN b ON a.k = b.k",
                  return_futures=False)
    d = _spmd_deltas(c0)
    assert d.get("spmd_exchange_joins", 0) >= 1, d
    assert d.get("spmd_exchanges", 0) >= 1, d
    assert d.get("spmd_exchange_bytes", 0) > 0, d
    want = (a.merge(b, on="k").eval("v * w")).sum()
    np.testing.assert_allclose(float(got["s"][0]), want, rtol=1e-9)


def test_mesh_kill_switch_restores_baseline(spmd_ctx, monkeypatch):
    ctx, fact, _ = spmd_ctx
    monkeypatch.setenv("DSQL_MESH", "0")
    c0 = tel.REGISTRY.counters()
    got = ctx.sql("SELECT COUNT(*) AS n FROM fact", return_futures=False)
    d = _spmd_deltas(c0)
    assert d.get("spmd_queries", 0) == 0, d
    assert int(got["n"][0]) == len(fact)


def test_system_mesh_table_reports_devices(spmd_ctx):
    ctx, _, _ = spmd_ctx
    got = ctx.sql("SELECT COUNT(*) AS n FROM system.mesh "
                  "WHERE in_mesh AND spmd_enabled", return_futures=False)
    assert int(got["n"][0]) == int(ctx.mesh.devices.size)


# ---------------------------------------------------------------------------
# cross-process program-store round-trip of a sharded stage program
# ---------------------------------------------------------------------------

_STORE_CHILD = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import numpy as np, pandas as pd
    from dask_sql_tpu import Context
    from dask_sql_tpu.parallel.mesh import default_mesh
    from dask_sql_tpu.runtime import telemetry as tel

    rng = np.random.RandomState(5)   # SAME data in both processes
    df = pd.DataFrame({"g": rng.randint(0, 6, 64).astype(np.int64),
                       "v": np.round(rng.rand(64), 6)})
    ctx = Context(mesh=default_mesh())
    ctx.create_table("t", df)
    out = ctx.sql("SELECT g, SUM(v) AS s FROM t GROUP BY g ORDER BY g",
                  return_futures=False)
    c = tel.REGISTRY.counters()
    json.dump({"s": [round(float(x), 9) for x in out["s"]],
               "spmd_queries": int(c.get("spmd_queries", 0)),
               "spmd_compiles": int(c.get("spmd_compiles", 0)),
               "spmd_store_hits": int(c.get("spmd_store_hits", 0))},
              sys.stdout)
""")


@pytest.mark.slow
def test_sharded_program_store_round_trip(tmp_path):
    import json

    env = dict(__import__("os").environ,
               DSQL_PROGRAM_STORE=str(tmp_path / "programs"),
               DSQL_MESH="1", DSQL_ADAPTIVE="0")
    env.pop("JAX_PLATFORMS", None)
    runs = []
    for _ in range(2):
        proc = subprocess.run([sys.executable, "-c", _STORE_CHILD],
                              capture_output=True, text=True, env=env,
                              timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        runs.append(json.loads(proc.stdout))
    first, second = runs
    assert first["spmd_queries"] == second["spmd_queries"] == 1
    assert first["spmd_compiles"] >= 1
    # the second process must serve the sharded stage program from the
    # persistent store without a single XLA compile
    assert second["spmd_compiles"] == 0, second
    assert second["spmd_store_hits"] >= 1, second
    assert first["s"] == second["s"]
