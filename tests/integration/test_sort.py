"""ORDER BY / LIMIT tests (reference: tests/integration/test_sort.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_sort(c, user_table_1):
    result = c.sql(
        "SELECT * FROM user_table_1 ORDER BY b, user_id DESC")
    expected = user_table_1.sort_values(["b", "user_id"], ascending=[True, False])
    assert_eq(result, expected)


def test_sort_desc(c, user_table_1):
    result = c.sql("SELECT * FROM user_table_1 ORDER BY b DESC")
    expected = user_table_1.sort_values("b", ascending=False, kind="stable")
    assert_eq(result, expected)


def test_sort_with_nan(c):
    frame = pd.DataFrame({"a": [1, 2, np.nan], "b": [4, np.nan, 5]})
    c.create_table("df_nan", frame)
    result = c.sql("SELECT * FROM df_nan ORDER BY a").to_pandas()
    # postgres default: NULLS LAST for ASC
    assert np.isnan(result["a"].iloc[-1])
    result = c.sql("SELECT * FROM df_nan ORDER BY a DESC").to_pandas()
    # NULLS FIRST for DESC
    assert np.isnan(result["a"].iloc[0])
    result = c.sql("SELECT * FROM df_nan ORDER BY a NULLS FIRST").to_pandas()
    assert np.isnan(result["a"].iloc[0])
    result = c.sql("SELECT * FROM df_nan ORDER BY a DESC NULLS LAST").to_pandas()
    assert np.isnan(result["a"].iloc[-1])


def test_sort_strings(c, string_table):
    result = c.sql("SELECT * FROM string_table ORDER BY a")
    expected = string_table.sort_values("a")
    assert_eq(result, expected)


def test_limit(c, long_table):
    assert_eq(c.sql("SELECT * FROM long_table LIMIT 101"), long_table.head(101))
    assert_eq(c.sql("SELECT * FROM long_table LIMIT 100"), long_table.head(100))
    assert_eq(
        c.sql("SELECT * FROM long_table LIMIT 100 OFFSET 99"),
        long_table.iloc[99 : 99 + 100],
    )
    assert_eq(c.sql("SELECT * FROM long_table OFFSET 170"), long_table.iloc[170:])


def test_sort_by_expression(c, user_table_1):
    result = c.sql("SELECT user_id FROM user_table_1 ORDER BY b + user_id, b")
    expected = user_table_1.assign(k=user_table_1["b"] + user_table_1["user_id"])
    expected = expected.sort_values(["k", "b"])[["user_id"]]
    assert_eq(result, expected)


def test_sort_by_ordinal(c, user_table_1):
    result = c.sql("SELECT user_id, b FROM user_table_1 ORDER BY 2, 1")
    expected = user_table_1.sort_values(["b", "user_id"])[["user_id", "b"]]
    assert_eq(result, expected)


def test_sort_with_limit_expression(c, long_table):
    result = c.sql("SELECT * FROM long_table ORDER BY a DESC LIMIT 10")
    expected = long_table.sort_values("a", ascending=False).head(10)
    assert_eq(result, expected)
