"""CREATE TABLE tests (reference: tests/integration/test_create.py)."""
import os
import tempfile

import pandas as pd
import pytest

from tests.conftest import assert_eq


@pytest.fixture()
def temporary_data_file():
    path = os.path.join(tempfile.gettempdir(), os.urandom(12).hex() + ".csv")
    yield path
    if os.path.exists(path):
        os.unlink(path)


def test_create_from_csv(c, df_simple, temporary_data_file):
    df_simple.to_csv(temporary_data_file, index=False)
    c.sql(f"""CREATE TABLE new_table WITH (
               location = '{temporary_data_file}', format = 'csv')""")
    assert_eq(c.sql("SELECT * FROM new_table"), df_simple)


def test_create_from_csv_persist(c, df_simple, temporary_data_file):
    df_simple.to_csv(temporary_data_file, index=False)
    c.sql(f"""CREATE TABLE new_table WITH (
               location = '{temporary_data_file}', format = 'csv', persist = True)""")
    assert_eq(c.sql("SELECT * FROM new_table"), df_simple)


def test_wrong_create(c):
    with pytest.raises(AttributeError):
        c.sql("CREATE TABLE new_table WITH (format = 'csv')")
    with pytest.raises(AttributeError):
        c.sql("CREATE TABLE new_table WITH (format = 'strange', location = 'x')")


def test_create_from_query(c, df_simple):
    c.sql("CREATE TABLE new_table AS (SELECT a + 1 AS a FROM df_simple)")
    assert_eq(c.sql("SELECT * FROM new_table"),
              pd.DataFrame({"a": df_simple["a"] + 1}))
    c.sql("CREATE OR REPLACE TABLE new_table AS (SELECT a - 1 AS a FROM df_simple)")
    assert_eq(c.sql("SELECT * FROM new_table"),
              pd.DataFrame({"a": df_simple["a"] - 1}))
    with pytest.raises(RuntimeError):
        c.sql("CREATE TABLE new_table AS (SELECT a FROM df_simple)")
    c.sql("CREATE TABLE IF NOT EXISTS new_table AS (SELECT a FROM df_simple)")


def test_create_view(c, df_simple):
    c.sql("CREATE VIEW my_view AS (SELECT a + 1 AS a FROM df_simple)")
    assert_eq(c.sql("SELECT * FROM my_view"),
              pd.DataFrame({"a": df_simple["a"] + 1}))
    # views are lazy: they see updates to the underlying table
    c.sql("CREATE OR REPLACE TABLE df_simple AS (SELECT 10 AS a, 1.0 AS b)")
    assert_eq(c.sql("SELECT * FROM my_view"), pd.DataFrame({"a": [11]}))


def test_drop_table(c, df_simple):
    c.create_table("to_drop", df_simple)
    c.sql("DROP TABLE to_drop")
    from dask_sql_tpu.utils import ParsingException
    with pytest.raises(ParsingException):
        c.sql("SELECT * FROM to_drop")
    with pytest.raises(RuntimeError):
        c.sql("DROP TABLE to_drop")
    c.sql("DROP TABLE IF EXISTS to_drop")


def test_create_from_parquet(c, df_simple, temporary_data_file):
    path = temporary_data_file.replace(".csv", ".parquet")
    df_simple.to_parquet(path)
    try:
        c.sql(f"CREATE TABLE pq_table WITH (location = '{path}')")
        assert_eq(c.sql("SELECT * FROM pq_table"), df_simple)
    finally:
        os.unlink(path)
