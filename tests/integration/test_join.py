"""JOIN tests (reference: tests/integration/test_join.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_join(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT lhs.user_id, lhs.b, rhs.c
           FROM user_table_1 AS lhs JOIN user_table_2 AS rhs
           ON lhs.user_id = rhs.user_id""")
    expected = user_table_1.merge(user_table_2, on="user_id")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_inner(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT lhs.user_id, lhs.b, rhs.c
           FROM user_table_1 AS lhs INNER JOIN user_table_2 AS rhs
           ON lhs.user_id = rhs.user_id""")
    expected = user_table_1.merge(user_table_2, on="user_id")[["user_id", "b", "c"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_outer(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT lhs.user_id, lhs.b, rhs.c
           FROM user_table_1 AS lhs FULL JOIN user_table_2 AS rhs
           ON lhs.user_id = rhs.user_id""")
    expected = user_table_1.merge(user_table_2, on="user_id", how="outer")[
        ["user_id", "b", "c"]]
    # SQL semantics: lhs.user_id is NULL for right-only rows (pandas merge
    # coalesces the key; SQL does not)
    expected.loc[expected["b"].isna(), "user_id"] = np.nan
    assert_eq(result, expected, check_row_order=False)


def test_join_left(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT lhs.user_id, lhs.b, rhs.c
           FROM user_table_1 AS lhs LEFT JOIN user_table_2 AS rhs
           ON lhs.user_id = rhs.user_id""")
    expected = user_table_1.merge(user_table_2, on="user_id", how="left")[
        ["user_id", "b", "c"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_right(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT rhs.user_id, lhs.b, rhs.c
           FROM user_table_1 AS lhs RIGHT JOIN user_table_2 AS rhs
           ON lhs.user_id = rhs.user_id""")
    expected = user_table_1.merge(user_table_2, on="user_id", how="right")[
        ["user_id", "b", "c"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_cross(c, user_table_1, df_simple):
    result = c.sql(
        "SELECT user_id, lhs.b, a FROM user_table_1 AS lhs, df_simple AS rhs")
    expected = user_table_1.merge(df_simple[["a"]], how="cross")[["user_id", "b", "a"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_complex(c, df_simple):
    result = c.sql(
        """SELECT lhs.a, rhs.b
           FROM df_simple AS lhs JOIN df_simple AS rhs
           ON lhs.a < rhs.b""")
    lhs = df_simple.rename(columns={"b": "lb"})
    rhs = df_simple.rename(columns={"a": "ra"})
    expected = lhs.merge(rhs, how="cross")
    expected = expected[expected["a"] < expected["b"]][["a", "b"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_equi_plus_residual(c, user_table_lk, user_table_ts):
    # equality + inequality condition (reference test pattern with lk tables)
    result = c.sql(
        """SELECT ts.dates, ts.ts_nullint, lk.id
           FROM user_table_ts ts JOIN user_table_lk lk
           ON lk.id = 1 AND ts.dates >= lk.startdate""")
    lk = user_table_lk[user_table_lk["id"] == 1]
    expected = user_table_ts.merge(lk, how="cross")
    expected = expected[expected["dates"] >= expected["startdate"]][
        ["dates", "ts_nullint", "id"]]
    assert_eq(result, expected, check_row_order=False)


def test_join_on_nan(c):
    left = pd.DataFrame({"k": [1.0, np.nan, 2.0], "v": [1, 2, 3]})
    right = pd.DataFrame({"k": [1.0, np.nan], "w": [10, 20]})
    c.create_table("jl", left)
    c.create_table("jr", right)
    result = c.sql("SELECT jl.v, jr.w FROM jl JOIN jr ON jl.k = jr.k").to_pandas()
    # NULL keys never match (SQL semantics)
    assert len(result) == 1
    assert result["v"][0] == 1 and result["w"][0] == 10


def test_join_usage_counts(c, user_table_1, user_table_2):
    # many-to-many expansion
    result = c.sql(
        """SELECT lhs.user_id FROM user_table_1 lhs
           JOIN user_table_2 rhs ON lhs.user_id = rhs.user_id""").to_pandas()
    expected = user_table_1.merge(user_table_2, on="user_id")
    assert len(result) == len(expected)


def test_join_using(c, user_table_1, user_table_2):
    result = c.sql(
        "SELECT * FROM user_table_1 JOIN user_table_2 USING (user_id)").to_pandas()
    expected = user_table_1.merge(user_table_2, on="user_id")
    # USING hides the duplicate column in star expansion
    assert list(result.columns) == ["user_id", "b", "c"]
    assert len(result) == len(expected)


def test_semi_join_via_in(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT * FROM user_table_1
           WHERE user_id IN (SELECT user_id FROM user_table_2)""")
    expected = user_table_1[user_table_1["user_id"].isin(user_table_2["user_id"])]
    assert_eq(result, expected, check_row_order=False)


def test_anti_join_via_not_in(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT * FROM user_table_1
           WHERE user_id NOT IN (SELECT user_id FROM user_table_2)""")
    expected = user_table_1[~user_table_1["user_id"].isin(user_table_2["user_id"])]
    assert_eq(result, expected, check_row_order=False)


def test_exists(c, user_table_1, user_table_2):
    result = c.sql(
        """SELECT * FROM user_table_1
           WHERE EXISTS (SELECT 1 FROM user_table_2 WHERE c > 100)""").to_pandas()
    assert len(result) == 0


def test_scalar_subquery(c, user_table_1):
    result = c.sql(
        "SELECT * FROM user_table_1 WHERE b < (SELECT AVG(b) FROM user_table_1)")
    expected = user_table_1[user_table_1["b"] < user_table_1["b"].mean()]
    assert_eq(result, expected, check_row_order=False)


def test_self_join(c, user_table_1):
    result = c.sql(
        """SELECT a.user_id FROM user_table_1 a
           JOIN user_table_1 b ON a.user_id = b.user_id""").to_pandas()
    expected = user_table_1.merge(user_table_1, on="user_id")
    assert len(result) == len(expected)


def test_correlated_count_zero_matches(c):
    """WHERE 0 = (SELECT COUNT(*) ... correlated) must keep outer rows with
    no matches: the decorrelation uses a LEFT join + COALESCE, not the
    INNER-join rewrite that silently drops empty groups."""
    import pandas as pd
    c.create_table("cc_l", pd.DataFrame({"k": [1, 2, 3]}))
    c.create_table("cc_r", pd.DataFrame({"k": [1, 1, 3]}))
    r = c.sql("SELECT k FROM cc_l WHERE 0 = "
              "(SELECT COUNT(*) FROM cc_r WHERE cc_r.k = cc_l.k)",
              return_futures=False)
    assert r["k"].tolist() == [2]
    r2 = c.sql("SELECT k FROM cc_l WHERE 2 = "
               "(SELECT COUNT(*) FROM cc_r WHERE cc_r.k = cc_l.k)",
               return_futures=False)
    assert r2["k"].tolist() == [1]


def test_correlated_exists_and_scalar(c):
    import pandas as pd
    c.create_table("ce_o", pd.DataFrame({"k": [1, 2, 3], "v": [10.0, 20.0, 30.0]}))
    c.create_table("ce_i", pd.DataFrame({"k": [1, 1, 2], "w": [5.0, 25.0, 10.0]}))
    r = c.sql("SELECT k FROM ce_o WHERE EXISTS "
              "(SELECT * FROM ce_i WHERE ce_i.k = ce_o.k AND w > 6)",
              return_futures=False)
    assert sorted(r["k"].tolist()) == [1, 2]
    r2 = c.sql("SELECT k FROM ce_o WHERE v > "
               "(SELECT AVG(w) FROM ce_i WHERE ce_i.k = ce_o.k)",
               return_futures=False)
    assert sorted(r2["k"].tolist()) == [2]
