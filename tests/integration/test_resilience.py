"""End-to-end resilience: the fault-injection matrix over every named site
× {retry-succeeds, degrades-one-rung / typed failure, deadline-exceeded},
plus server-level cancellation and timeout payloads.

The acceptance bar (ISSUE 2): with a fault injected at any site, affected
queries still return ORACLE-CORRECT results via the degradation ladder and
``compiled.stats`` records the retry/degradation; with the eager rung
disabled a typed TransientError surfaces — never a wrong answer, never a
hang past the deadline, never a leaked ``__split__`` temp."""
import os
import time

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import faults, resilience as R
from tests.conftest import assert_eq

AGG_Q = "SELECT user_id, SUM(b) AS sb FROM user_table_1 GROUP BY user_id"
JOIN_Q = ("SELECT u1.user_id, SUM(u2.c) AS s FROM user_table_1 u1 "
          "JOIN user_table_2 u2 ON u1.user_id = u2.user_id "
          "GROUP BY u1.user_id")

_needs_compiled = pytest.mark.skipif(
    os.environ.get("DSQL_COMPILE") == "0",
    reason="fault sites live on the compiled path")


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    """Per-test isolation: cached programs would bypass the compile site,
    and an armed spec must never leak into the next test."""
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()
    faults.reset()
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "1")
    yield
    faults.reset()


def _eager_oracle(c, query) -> pd.DataFrame:
    prev = os.environ.get("DSQL_COMPILE")
    os.environ["DSQL_COMPILE"] = "0"
    try:
        return c.sql(query, return_futures=False)
    finally:
        if prev is None:
            del os.environ["DSQL_COMPILE"]
        else:
            os.environ["DSQL_COMPILE"] = prev


def _no_split_leak(c):
    sch = c.schema.get("__split__")
    assert sch is None or not sch.tables, "leaked __split__ temp tables"


@pytest.fixture()
def chunked_ctx():
    df = pd.DataFrame({"k": [1, 2, 1, 2, 1, 2, 1, 2],
                       "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]})
    ctx = Context()
    ctx.create_table("t", df, chunked=True, batch_rows=3)
    expected = (df.groupby("k", as_index=False).agg(s=("v", "sum"))
                  .rename(columns={"k": "k"}))
    return ctx, expected


CHUNK_Q = "SELECT k, SUM(v) AS s FROM t GROUP BY k"


# ---------------------------------------------------------------------------
# retry-succeeds: one injected blip, same answer, retries counted
# ---------------------------------------------------------------------------

@_needs_compiled
@pytest.mark.parametrize("site", ["compile", "materialize"])
def test_single_fault_retries_and_succeeds(c, site):
    expected = _eager_oracle(c, AGG_Q)
    r0, f0 = compiled.stats["retries"], compiled.stats[f"fault_{site}"]
    with faults.inject(f"{site}:1"):
        got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats[f"fault_{site}"] == f0 + 1
    assert compiled.stats["retries"] >= r0 + 1


@_needs_compiled
def test_stage_exec_fault_retries_and_succeeds(c, monkeypatch):
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    expected = _eager_oracle(c, JOIN_Q)
    g0 = compiled.stats["stage_graphs"]
    r0, f0 = compiled.stats["retries"], compiled.stats["fault_stage_exec"]
    with faults.inject("stage_exec:1"):
        got = c.sql(JOIN_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["stage_graphs"] > g0, "plan did not stage"
    assert compiled.stats["fault_stage_exec"] == f0 + 1
    assert compiled.stats["retries"] >= r0 + 1
    _no_split_leak(c)


@pytest.mark.parametrize("site", ["chunked_read", "host_transfer"])
def test_streaming_fault_retries_and_succeeds(chunked_ctx, site):
    ctx, expected = chunked_ctx
    r0, f0 = compiled.stats["retries"], compiled.stats[f"fault_{site}"]
    with faults.inject(f"{site}:1"):
        got = ctx.sql(CHUNK_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats[f"fault_{site}"] == f0 + 1
    assert compiled.stats["retries"] >= r0 + 1


# ---------------------------------------------------------------------------
# degrades-one-rung: persistent fault, answer still oracle-correct via a
# lower rung (stages → eager), degradation recorded
# ---------------------------------------------------------------------------

@_needs_compiled
@pytest.mark.parametrize("site", ["compile", "materialize"])
def test_persistent_fault_degrades_to_eager(c, site):
    expected = _eager_oracle(c, AGG_Q)
    d0 = compiled.stats["degradations"]
    with faults.inject(f"{site}:1+"):
        got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["degradations"] >= d0 + 1


@_needs_compiled
def test_persistent_compile_fault_walks_whole_stages_eager(c, monkeypatch):
    """A heavy plan walks the DECLARED ladder: whole-plan jit fails →
    bounded stages (split hint) → stages fail → eager — still correct."""
    expected = _eager_oracle(c, JOIN_Q)
    d0, h0 = compiled.stats["degradations"], compiled.stats["split_hints"]
    with faults.inject("compile:1+"):
        got = c.sql(JOIN_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["split_hints"] >= h0 + 1, "whole→stages rung"
    assert compiled.stats["degradations"] >= d0 + 2, "stages→eager rung"
    _no_split_leak(c)


@_needs_compiled
def test_persistent_stage_fault_degrades_graph_to_eager(c, monkeypatch):
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    expected = _eager_oracle(c, JOIN_Q)
    d0 = compiled.stats["degradations"]
    with faults.inject("stage_exec:1+"):
        got = c.sql(JOIN_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["degradations"] >= d0 + 1
    _no_split_leak(c)


@pytest.mark.parametrize("site", ["chunked_read", "host_transfer"])
def test_streaming_persistent_fault_surfaces_typed(chunked_ctx, site):
    """The streaming sites have no lower rung (the data IS the input):
    exhausted retries surface the typed TransientError — never a partial
    or wrong result."""
    ctx, _ = chunked_ctx
    with faults.inject(f"{site}:1+"):
        with pytest.raises(R.TransientError):
            ctx.sql(CHUNK_Q)


@_needs_compiled
def test_eager_disabled_surfaces_typed_error(c, monkeypatch):
    """DSQL_EAGER_FALLBACK=0 turns the ladder's last rung into a TYPED
    failure (the acceptance criterion's fail-fast mode)."""
    monkeypatch.setenv("DSQL_EAGER_FALLBACK", "0")
    with faults.inject("compile:1+"):
        with pytest.raises(R.TransientError):
            c.sql(AGG_Q)


@_needs_compiled
def test_transient_failure_does_not_exile(c):
    """A transient-exhausted degrade must NOT poison the program cache:
    the next call (fault disarmed) compiles and serves compiled."""
    with faults.inject("compile:1+"):
        c.sql(AGG_Q, return_futures=False)
    n0 = compiled.stats["compiles"]
    c.sql(AGG_Q, return_futures=False)
    assert compiled.stats["compiles"] == n0 + 1, "plan was wrongly exiled"


# ---------------------------------------------------------------------------
# deadline-exceeded: a stalled site must surface the typed verdict well
# before the stall ends — never a hang past the deadline
# ---------------------------------------------------------------------------

@_needs_compiled
@pytest.mark.parametrize("site,query_fixture", [
    ("compile", "resident"), ("materialize", "resident"),
    ("stage_exec", "resident_staged"),
    ("chunked_read", "chunked"), ("host_transfer", "chunked"),
])
def test_stalled_site_hits_deadline(c, chunked_ctx, monkeypatch, site,
                                    query_fixture):
    if query_fixture == "resident":
        ctx, query = c, AGG_Q
    elif query_fixture == "resident_staged":
        monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
        ctx, query = c, JOIN_Q
    else:
        ctx, query = chunked_ctx[0], CHUNK_Q
    dl0 = compiled.stats["deadline_exceeded"]
    t0 = time.monotonic()
    with faults.inject(f"{site}:1:sleep=60000"):
        with pytest.raises(R.DeadlineExceeded):
            ctx.sql(query, timeout=0.5)
    assert time.monotonic() - t0 < 30.0, "ran far past the deadline"
    assert compiled.stats["deadline_exceeded"] > dl0


def test_sql_timeout_zero_is_immediate(c):
    with pytest.raises(R.DeadlineExceeded):
        c.sql(AGG_Q, timeout=0.0)


def test_deadline_applies_to_eager_path_too(c, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE", "0")
    with pytest.raises(R.DeadlineExceeded):
        c.sql(AGG_Q, timeout=0.0)


# ---------------------------------------------------------------------------
# server: typed payloads, timeout shape, cancel-while-compiling
# ---------------------------------------------------------------------------

@pytest.fixture()
def server():
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table(
        "df", pd.DataFrame({"a": [1, 2, 3], "b": [1.5, 2.5, 3.5]}))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield srv, f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _post(url, body):
    import json
    import urllib.request
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(url):
    import json
    import urllib.request
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def _poll(base, payload, timeout=60):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        payload = _get(payload["nextUri"])
    return payload


@_needs_compiled
def test_server_timeout_payload_shape(server, monkeypatch):
    srv, base = server
    monkeypatch.setenv("DSQL_QUERY_TIMEOUT_MS", "400")
    with faults.inject("compile:1:sleep=60000"):
        payload = _poll(base, _post(
            f"{base}/v1/statement", "SELECT a, SUM(b) AS s FROM df GROUP BY a"))
    err = payload["error"]
    assert payload["stats"]["state"] == "FAILED"
    assert err["errorType"] == "INSUFFICIENT_RESOURCES"
    assert err["errorName"] == "EXCEEDED_TIME_LIMIT"
    assert err["errorCode"] == R.DeadlineExceeded("x").error_code


@_needs_compiled
def test_server_cancel_while_compiling(server):
    """DELETE /v1/cancel must abort a query stuck in compile: the cancel
    token (not fut.cancel(), a no-op on started futures) makes the worker
    raise QueryCancelled at its next checkpoint."""
    srv, base = server
    f0 = compiled.stats["fault_compile"]
    with faults.inject("compile:1:sleep=60000"):
        payload = _post(f"{base}/v1/statement",
                        "SELECT a, SUM(b) AS s FROM df GROUP BY a")
        uid = payload["id"]
        # wait until the worker is inside the stalled compile
        deadline = time.time() + 30
        while (compiled.stats["fault_compile"] == f0
               and time.time() < deadline):
            time.sleep(0.02)
        fut = srv.app_state.future_list[uid]
        import urllib.request
        req = urllib.request.Request(payload["partialCancelUri"],
                                     method="DELETE")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        t0 = time.monotonic()
        exc = fut.exception(timeout=30)
    assert isinstance(exc, R.QueryCancelled)
    assert time.monotonic() - t0 < 30.0, "cancel did not interrupt compile"


def test_server_internal_error_payload(server):
    """An engine-side transient that exhausts the ladder with eager
    disabled maps to INTERNAL_ERROR — not a stringified USER_ERROR."""
    srv, base = server
    os.environ["DSQL_EAGER_FALLBACK"] = "0"
    try:
        with faults.inject("compile:1+"):
            payload = _poll(base, _post(
                f"{base}/v1/statement",
                "SELECT a, SUM(b) AS s FROM df GROUP BY a"))
    finally:
        del os.environ["DSQL_EAGER_FALLBACK"]
    err = payload["error"]
    assert err["errorType"] == "INTERNAL_ERROR"
    assert err["errorName"] == "FAULT_INJECTED"
    assert err["errorCode"] == R.TransientError("x").error_code


def test_server_user_error_still_user_error(server):
    srv, base = server
    payload = _poll(base, _post(f"{base}/v1/statement",
                                "SELECT * FROM missing_table"))
    assert payload["error"]["errorType"] == "USER_ERROR"
    assert "errorLocation" in payload["error"]
