"""A big multi-clause query (reference: tests/integration/test_complex.py)."""
import numpy as np
import pandas as pd


def test_complex_query(c):
    rng = np.random.RandomState(42)
    n = 500
    frame = pd.DataFrame({
        "user_id": rng.randint(0, 20, n),
        "category": rng.choice(["a", "b", "c", "d"], n),
        "amount": np.round(rng.uniform(0, 100, n), 2),
        "ts": pd.to_datetime(
            rng.randint(1577836800, 1609459200, n), unit="s"),
    })
    c.create_table("events", frame)

    result = c.sql(
        """
        WITH spend AS (
            SELECT user_id, category, SUM(amount) AS total,
                   COUNT(*) AS n_events
            FROM events
            WHERE EXTRACT(YEAR FROM ts) = 2020
            GROUP BY user_id, category
        )
        SELECT s.category,
               COUNT(*) AS n_users,
               SUM(s.total) AS category_total,
               AVG(s.total) AS avg_user_total,
               MAX(s.n_events) AS max_events
        FROM spend s
        WHERE s.total > (SELECT AVG(total) * 0.5 FROM spend)
        GROUP BY s.category
        HAVING COUNT(*) > 1
        ORDER BY category_total DESC
        """).to_pandas()

    # pandas cross-check
    f = frame[frame["ts"].dt.year == 2020]
    spend = f.groupby(["user_id", "category"]).agg(
        total=("amount", "sum"), n_events=("amount", "count")).reset_index()
    spend = spend[spend["total"] > spend["total"].mean() * 0.5]
    exp = spend.groupby("category").agg(
        n_users=("total", "count"), category_total=("total", "sum"),
        avg_user_total=("total", "mean"), max_events=("n_events", "max"),
    ).reset_index()
    exp = exp[exp["n_users"] > 1].sort_values("category_total", ascending=False)

    np.testing.assert_array_equal(result["category"].values, exp["category"].values)
    np.testing.assert_allclose(result["category_total"].values,
                               exp["category_total"].values, rtol=1e-9)
    np.testing.assert_allclose(result["avg_user_total"].values,
                               exp["avg_user_total"].values, rtol=1e-9)


def test_tpch_q1_small(c):
    from benchmarks.tpch import QUERIES, generate_tpch

    data = generate_tpch(0.001)
    for name, frame in data.items():
        c.create_table(name, frame)
    result = c.sql(QUERIES[1]).to_pandas()

    li = data["lineitem"]
    d = li[li["l_shipdate"] <= pd.Timestamp("1998-09-02")].copy()
    d["disc_price"] = d["l_extendedprice"] * (1 - d["l_discount"])
    d["charge"] = d["disc_price"] * (1 + d["l_tax"])
    exp = d.groupby(["l_returnflag", "l_linestatus"]).agg(
        sum_qty=("l_quantity", "sum"), sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"), sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"), avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"), count_order=("l_quantity", "size"),
    ).reset_index().sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)

    assert list(result["l_returnflag"]) == list(exp["l_returnflag"])
    np.testing.assert_allclose(result["sum_disc_price"], exp["sum_disc_price"], rtol=1e-9)
    np.testing.assert_allclose(result["avg_disc"], exp["avg_disc"], rtol=1e-9)
    np.testing.assert_array_equal(result["count_order"], exp["count_order"])


def test_tpch_q3_q6_small(c):
    from benchmarks.tpch import QUERIES, generate_tpch

    data = generate_tpch(0.001)
    for name, frame in data.items():
        c.create_table(name, frame)

    r6 = c.sql(QUERIES[6]).to_pandas()
    li = data["lineitem"]
    d = li[(li["l_shipdate"] >= pd.Timestamp("1994-01-01"))
           & (li["l_shipdate"] < pd.Timestamp("1995-01-01"))
           & (li["l_discount"] >= 0.05) & (li["l_discount"] <= 0.07)
           & (li["l_quantity"] < 24)]
    expected6 = (d["l_extendedprice"] * d["l_discount"]).sum()
    np.testing.assert_allclose(r6.iloc[0, 0], expected6, rtol=1e-9)

    r3 = c.sql(QUERIES[3]).to_pandas()
    cu, od = data["customer"], data["orders"]
    m = (cu[cu["c_mktsegment"] == "BUILDING"]
         .merge(od[od["o_orderdate"] < pd.Timestamp("1995-03-15")],
                left_on="c_custkey", right_on="o_custkey")
         .merge(li[li["l_shipdate"] > pd.Timestamp("1995-03-15")],
                left_on="o_orderkey", right_on="l_orderkey"))
    m["revenue"] = m["l_extendedprice"] * (1 - m["l_discount"])
    exp3 = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])["revenue"]
            .sum().reset_index().sort_values(["revenue", "o_orderdate"],
                                             ascending=[False, True]).head(10))
    np.testing.assert_allclose(sorted(r3["revenue"]), sorted(exp3["revenue"]), rtol=1e-9)
