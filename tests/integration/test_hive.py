"""Hive ingestion against a fake metastore cursor.

The reference tests this against dockerized Hive containers
(tests/integration/test_hive.py:37-60); no docker here, so the cursor is a
test double that replays the exact DESCRIBE FORMATTED / SHOW PARTITIONS wire
rows a Hive server produces, over real parquet/csv files on disk. This
exercises the full parse -> read -> partition-column -> Table path.
"""
import os

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.io.hive import (
    hive_table_to_pandas, parse_hive_table_description,
)


class FakeHiveCursor:
    """Replays canned (key, value, value2) rows like a pyhive cursor."""

    def __init__(self, responses):
        self.responses = responses
        self._last = []

    def execute(self, sql):
        sql = " ".join(sql.split())
        self._last = self.responses.get(sql, [])
        return self  # sqlalchemy style: result has fetchall

    def fetchall(self):
        return self._last


def _describe_rows(columns, location, input_format, partitions=None,
                   field_delim=None, detail_extra=()):
    rows = [("# col_name", "data_type", "comment")]
    rows += [(name, typ, "") for name, typ in columns]
    if partitions:
        rows.append(("# Partition Information", "", ""))
        rows.append(("# col_name", "data_type", "comment"))
        rows += [(name, typ, "") for name, typ in partitions]
    rows.append(("# Detailed Table Information", "", ""))
    rows.append(("Location", location, ""))
    rows += list(detail_extra)  # e.g. Partition Value for partition describes
    rows.append(("# Storage Information", "", ""))
    rows.append(("InputFormat", input_format, ""))
    if field_delim:
        rows.append(("Storage Desc Params", "", ""))
        rows.append(("", "field.delim", field_delim))
    return rows


PARQUET_FMT = "org.apache.hadoop.hive.ql.io.parquet.MapredParquetInputFormat"
TEXT_FMT = "org.apache.hadoop.mapred.TextInputFormat"


@pytest.fixture()
def parquet_table(tmp_path):
    d = tmp_path / "warehouse" / "tbl"
    d.mkdir(parents=True)
    df = pd.DataFrame({"i": np.arange(5, dtype="int32"),
                       "s": ["a", "b", "c", "d", "e"]})
    df.to_parquet(d / "part-0000")
    return d, df


def test_describe_formatted_parse(parquet_table):
    d, _ = parquet_table
    cursor = FakeHiveCursor({
        "USE default": [],
        "DESCRIBE FORMATTED tbl": _describe_rows(
            [("i", "int"), ("s", "string")], str(d), PARQUET_FMT),
    })
    cols, table, storage, parts = parse_hive_table_description(
        cursor, "default", "tbl")
    assert list(cols) == ["i", "s"]
    assert table["Location"] == str(d)
    assert storage["InputFormat"] == PARQUET_FMT
    assert parts == {}


def test_unpartitioned_parquet(parquet_table):
    d, df = parquet_table
    cursor = FakeHiveCursor({
        "USE default": [],
        "DESCRIBE FORMATTED tbl": _describe_rows(
            [("i", "int"), ("s", "string")], str(d), PARQUET_FMT),
    })
    got = hive_table_to_pandas(cursor, "tbl")
    pd.testing.assert_frame_equal(got.reset_index(drop=True), df,
                                  check_dtype=False)


def test_partitioned_csv(tmp_path):
    base = tmp_path / "wh" / "t2"
    frames = {}
    for part in ("p=1", "p=2"):
        d = base / part
        d.mkdir(parents=True)
        df = pd.DataFrame({"x": [1, 2] if part == "p=1" else [3, 4]})
        df.to_csv(d / "data-000", index=False, header=False)
        frames[part] = df
    common = dict(field_delim=",")
    cursor = FakeHiveCursor({
        "USE default": [],
        "DESCRIBE FORMATTED t2": _describe_rows(
            [("x", "bigint")], str(base), TEXT_FMT,
            partitions=[("p", "int")], **common),
        "SHOW PARTITIONS t2": [("p=1",), ("p=2",)],
        "DESCRIBE FORMATTED t2 PARTITION (p=1)": _describe_rows(
            [("x", "bigint")], str(base / "p=1"), TEXT_FMT,
            detail_extra=[("Partition Value", "[1]", "")], **common),
        "DESCRIBE FORMATTED t2 PARTITION (p=2)": _describe_rows(
            [("x", "bigint")], str(base / "p=2"), TEXT_FMT,
            detail_extra=[("Partition Value", "[2]", "")], **common),
    })
    got = hive_table_to_pandas(cursor, "t2")
    assert got["x"].tolist() == [1, 2, 3, 4]
    assert got["p"].tolist() == [1, 1, 2, 2]
    assert got["p"].dtype == np.int32


def test_hive_table_through_context_sql(parquet_table):
    d, _ = parquet_table
    cursor = FakeHiveCursor({
        "USE default": [],
        "DESCRIBE FORMATTED tbl": _describe_rows(
            [("i", "int"), ("s", "string")], str(d), PARQUET_FMT),
    })
    c = Context()
    c.create_table("hive_t", cursor, format="hive", hive_table_name="tbl")
    r = c.sql("SELECT s, i FROM hive_t WHERE i >= 3 ORDER BY i",
              return_futures=False)
    assert r["s"].tolist() == ["d", "e"]
    assert r["i"].tolist() == [3, 4]
