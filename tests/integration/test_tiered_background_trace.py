"""Chrome-trace capture for background compile daemon threads
(physical/compiled._background_compile): the daemon carries its own
``background_compile`` trace, so DSQL_CHROME_TRACE_DIR sees the compile
spans that previously ran outside any QueryTrace and vanished."""
import json
import os
import time

import numpy as np
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import telemetry as tel

_needs_compiled = pytest.mark.skipif(
    os.environ.get("DSQL_COMPILE") == "0",
    reason="background compiles need the compiled path")


@_needs_compiled
def test_background_compile_emits_chrome_trace(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_TIERED", "1")
    monkeypatch.setenv("DSQL_CHROME_TRACE_DIR", str(tmp_path))
    done0 = tel.REGISTRY.get("background_compiles_done")
    err0 = tel.REGISTRY.get("background_compile_errors")

    c = Context()
    c.create_table("t", {"a": np.arange(128, dtype=np.int64) % 7,
                         "b": np.arange(128, dtype=np.float64)})
    # cold plan: answered on the eager tier while the daemon compiles
    c.sql("SELECT a, SUM(b) AS s FROM t GROUP BY a")

    deadline = time.time() + 120
    while time.time() < deadline:
        if (tel.REGISTRY.get("background_compiles_done") > done0
                or tel.REGISTRY.get("background_compile_errors") > err0):
            break
        time.sleep(0.05)
    else:
        pytest.fail("background compile never finished")

    bg_blobs = []
    for f in sorted(tmp_path.glob("*.trace.json")):
        blob = json.loads(f.read_text())
        names = {e.get("name") for e in blob.get("traceEvents", [])}
        if "background_compile" in names:
            bg_blobs.append(blob)
    assert bg_blobs, "no chrome trace carries the background_compile root"
    # the daemon's trace contains the compile work itself, not just a root
    events = bg_blobs[0]["traceEvents"]
    assert len(events) > 1
    assert all(e.get("dur", 0) >= 0 for e in events)
