"""Multi-device kernel tests on the virtual 8-device CPU mesh (SURVEY §4:
same suite, mesh via env switch)."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dask_sql_tpu.parallel import distributed as D
from dask_sql_tpu.parallel.mesh import default_mesh, row_sharding, shard_table


@pytest.fixture(scope="module")
def mesh():
    m = default_mesh()
    if m.devices.size < 2:
        pytest.skip("needs a multi-device mesh")
    return m


def _shard(mesh, x):
    return jax.device_put(jnp.asarray(x), row_sharding(mesh))


def test_dist_segment_sum(mesh):
    n = 64
    codes = np.random.RandomState(0).randint(0, 10, n)
    vals = np.random.RandomState(1).rand(n)
    out = D.dist_segment_sum(mesh, _shard(mesh, vals), _shard(mesh, codes), 10)
    ref = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(codes), 10)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)


def test_hash_exchange_preserves_rows(mesh):
    n = 64
    codes = np.random.RandomState(0).randint(0, 13, n).astype(np.int64)
    vals = np.arange(n, dtype=np.float64)
    new_codes, new_vals = D.hash_exchange(mesh, _shard(mesh, codes), _shard(mesh, vals))
    nc = np.asarray(new_codes)
    nv = np.asarray(new_vals)
    kept = nc >= 0
    # every row arrives exactly once
    assert kept.sum() == n
    assert sorted(nv[kept]) == sorted(vals)
    # rows with equal key land on the same device shard
    per_dev = nc.reshape(mesh.devices.size, -1)
    owner = {}
    for d in range(mesh.devices.size):
        for code in per_dev[d][per_dev[d] >= 0]:
            assert owner.setdefault(int(code), d) == d


def test_dist_groupby_sum_exchange(mesh):
    n = 128
    codes = np.random.RandomState(3).randint(0, 20, n).astype(np.int64)
    vals = np.random.RandomState(4).rand(n)
    out = D.dist_groupby_sum_exchange(mesh, _shard(mesh, codes), _shard(mesh, vals), 20)
    ref = jax.ops.segment_sum(jnp.asarray(vals), jnp.asarray(codes), 20)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-12)


def test_dist_prefix_sum(mesh):
    n = 64
    vals = np.random.RandomState(5).rand(n)
    out = D.dist_prefix_sum(mesh, _shard(mesh, vals))
    np.testing.assert_allclose(np.asarray(out), np.cumsum(vals), rtol=1e-12)


def test_dist_join_broadcast(mesh):
    n = 64
    build_codes = (np.arange(n) % 8).astype(np.int64)
    build_vals = np.arange(n, dtype=np.float64)
    # make build keys unique: keep first occurrence semantics via unique codes
    build_codes = np.arange(n, dtype=np.int64)
    probe = np.random.RandomState(6).randint(0, 2 * n, n).astype(np.int64)
    got = D.dist_join_broadcast(mesh, _shard(mesh, probe),
                                _shard(mesh, build_codes), _shard(mesh, build_vals),
                                -1.0)
    exp = np.where(probe < n, probe.astype(np.float64), -1.0)
    np.testing.assert_allclose(np.asarray(got), exp)


def test_ring_shift(mesh):
    k = mesh.devices.size
    x = np.arange(k * 4, dtype=np.float64)
    out = np.asarray(D.ring_shift(mesh, _shard(mesh, x), 1))
    shifted = np.roll(x.reshape(k, 4), 1, axis=0).reshape(-1)
    np.testing.assert_allclose(out, shifted)


def test_shard_table_roundtrip(mesh):
    import pandas as pd
    from dask_sql_tpu.table import Table

    df = pd.DataFrame({"a": np.arange(10), "s": list("abcabcabca")})
    t = Table.from_pandas(df)
    st, n = shard_table(t, mesh)
    assert n == 10
    assert st.num_rows % mesh.devices.size == 0
    # padded rows are masked invalid
    assert st.columns[0].valid_mask().sum() == 10


def test_engine_on_sharded_input(mesh, c):
    """End-to-end: eager kernels run transparently on sharded arrays
    (computation follows data; XLA inserts collectives)."""
    import pandas as pd
    from dask_sql_tpu.table import Table

    n = 80
    df = pd.DataFrame({
        "g": np.random.RandomState(0).randint(0, 5, n),
        "v": np.random.RandomState(1).rand(n),
    })
    t = Table.from_pandas(df)
    st, _ = shard_table(t, mesh)
    c.create_table("sharded_t", st)
    result = c.sql(
        "SELECT g, SUM(v) AS s, COUNT(*) AS n FROM sharded_t GROUP BY g ORDER BY g"
    ).to_pandas()
    exp = df.groupby("g")["v"].agg(["sum", "count"]).reset_index()
    np.testing.assert_allclose(result["s"], exp["sum"], rtol=1e-9)
    np.testing.assert_array_equal(result["n"], exp["count"])


@pytest.mark.skipif(os.environ.get("DSQL_COMPILE") == "0",
                    reason="asserts compiled-path usage")
def test_context_mesh_mode_compiled(mesh):
    """Context(mesh=...): tables row-shard over the mesh (with padding +
    table validity) and queries run through the compiled SPMD path."""
    import pandas as pd
    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled

    n = 83  # deliberately not divisible by 8: exercises pad + row_valid
    rng = np.random.RandomState(7)
    df = pd.DataFrame({
        "g": rng.choice(["a", "b", "c"], n),
        "k": rng.randint(0, 20, n),
        "v": rng.rand(n),
    })
    dim = pd.DataFrame({"k": np.arange(20), "w": np.arange(20) * 0.5})

    plain = Context()
    plain.create_table("t", df)
    plain.create_table("d", dim)
    dist = Context(mesh=mesh)
    dist.create_table("t", df)
    dist.create_table("d", dim)

    queries = [
        "SELECT COUNT(*) AS n, SUM(v) AS s FROM t",
        "SELECT g, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY g ORDER BY g",
        "SELECT t.g, d.w FROM t JOIN d ON t.k = d.k ORDER BY t.v LIMIT 10",
        "SELECT * FROM t WHERE v > 0.5 ORDER BY v DESC LIMIT 5",
    ]
    for q in queries:
        before = compiled.stats["compiles"] + compiled.stats["hits"]
        before_fb = compiled.stats["fallbacks"]
        got = dist.sql(q, return_futures=False)
        assert compiled.stats["compiles"] + compiled.stats["hits"] > before, q
        # a runtime fallback would mean the eager path produced the result
        # and the SPMD program was never actually the execution vehicle
        assert compiled.stats["fallbacks"] == before_fb, q
        want = plain.sql(q, return_futures=False)
        pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                      want.reset_index(drop=True),
                                      check_dtype=False)


def test_mesh_mode_count_ignores_padding(mesh):
    import pandas as pd
    from dask_sql_tpu import Context

    df = pd.DataFrame({"x": np.arange(13.0)})  # pads to 16 on 8 devices
    c = Context(mesh=mesh)
    c.create_table("t", df)
    r = c.sql("SELECT COUNT(*) AS n, SUM(x) AS s FROM t",
              return_futures=False)
    assert r["n"][0] == 13
    assert r["s"][0] == 78.0


def test_init_multihost_single_host(mesh):
    """Without a coordinator the helper degrades to the local mesh."""
    from dask_sql_tpu.parallel.mesh import init_multihost

    m = init_multihost()
    assert m.devices.size == len(jax.devices())
