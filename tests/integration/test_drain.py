"""Graceful server drain (ISSUE 6): SIGTERM during an in-flight query
returns that query's FULL result, a concurrent new POST answers 503 +
Retry-After with the typed SERVER_SHUTTING_DOWN payload, and the server
stops within DSQL_DRAIN_TIMEOUT_S; stragglers past the budget get typed
cancellation, never an abandoned thread."""
import json
import os
import signal
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import faults, scheduler as sched, telemetry as tel
from dask_sql_tpu.server.app import install_drain_handlers, run_server

QUERY = "SELECT a, SUM(b) AS s FROM df GROUP BY a"


def _post(url, body):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def _poll(base, payload, timeout=60):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        payload = _get(payload["nextUri"])
    return payload


@pytest.fixture()
def server(monkeypatch):
    monkeypatch.setenv("DSQL_DRAIN_TIMEOUT_S", "20")
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "1")
    context = Context()
    context.create_table(
        "df", pd.DataFrame({"a": [1, 2, 3, 1], "b": [1.5, 2.5, 3.5, 0.5]}))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield srv, f"http://127.0.0.1:{srv.server_port}"
    # belt and braces: never leave the process-global manager draining or
    # the listener open for the next test module
    sched.get_manager().end_drain()
    try:
        srv.shutdown()
        srv.server_close()
    except Exception:
        pass


def _wait(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


@pytest.mark.skipif(os.environ.get("DSQL_COMPILE") == "0",
                    reason="uses the compile fault site to pace the query")
def test_sigterm_drains_inflight_then_503s_then_exits(server):
    """The acceptance proof, end to end with a REAL SIGTERM."""
    srv, base = server
    prev = install_drain_handlers(srv)
    assert prev, "handlers must install from the test's main thread"
    try:
        f0 = compiled.stats["fault_compile"]
        with faults.inject("compile:1:sleep=1500"):
            # in-flight query: stalls ~1.5 s in "compile", then retries
            # and completes with the full correct result
            payload = _post(f"{base}/v1/statement", QUERY)
            _wait(lambda: compiled.stats["fault_compile"] > f0,
                  what="worker inside the stalled compile")

            t0 = time.monotonic()
            os.kill(os.getpid(), signal.SIGTERM)
            _wait(lambda: sched.get_manager().draining(),
                  what="drain flag")
            assert tel.REGISTRY.get_gauge("server_draining") == 1

            # a concurrent new POST answers 503 + Retry-After, typed
            r0 = tel.REGISTRY.get("server_drain_rejects")
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(f"{base}/v1/statement", "SELECT 1")
            assert exc.value.code == 503
            assert int(exc.value.headers["Retry-After"]) >= 1
            body = json.loads(exc.value.read())
            assert body["error"]["errorName"] == "SERVER_SHUTTING_DOWN"
            assert body["error"]["errorType"] == "INSUFFICIENT_RESOURCES"
            assert tel.REGISTRY.get("server_drain_rejects") == r0 + 1

            # the in-flight query still delivers its FULL result
            result = _poll(base, payload)
        assert "error" not in result, result.get("error")
        got = {tuple(row) for row in result["data"]}
        assert got == {(1, 2.0), (2, 2.5), (3, 3.5)}

        # ... and the server exits well within DSQL_DRAIN_TIMEOUT_S
        assert srv.drained_event.wait(timeout=20), "drain never completed"
        assert time.monotonic() - t0 < 20.0
        assert not sched.get_manager().draining()
        assert tel.REGISTRY.get_gauge("server_draining") == 0
        with pytest.raises((urllib.error.URLError, ConnectionError)):
            _post(f"{base}/v1/statement", "SELECT 1")
    finally:
        for sig, h in prev.items():
            signal.signal(sig, h)


def test_drain_report_and_fault_site(server, monkeypatch):
    """drain_async records a ``drain`` span in a QueryReport, and an
    injected fault at the new ``drain`` site is swallowed — a broken
    drain step can never wedge process exit."""
    srv, base = server
    monkeypatch.setenv("DSQL_DRAIN_TIMEOUT_S", "5")
    d0 = tel.REGISTRY.get("fault_drain")
    with faults.inject("drain:1"):
        srv.drain_async("test-drain")
        assert srv.drained_event.wait(timeout=15), \
            "injected drain fault wedged the drain"
    assert tel.REGISTRY.get("fault_drain") == d0 + 1
    # the drain ran under its own trace: a QueryReport was produced and
    # the gauge returned to 0 (the report itself lives on the drain
    # thread; the counter proves the traced span closed)
    assert tel.REGISTRY.get_gauge("server_draining") == 0


def test_drain_cancels_stragglers_typed(monkeypatch):
    """A query that cannot finish inside DSQL_DRAIN_TIMEOUT_S is cut with
    TYPED cancellation; the drain still completes on time."""
    monkeypatch.setenv("DSQL_DRAIN_TIMEOUT_S", "1")
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "1")
    context = Context()
    context.create_table("df", pd.DataFrame({"a": [1, 2], "b": [1.0, 2.0]}))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    base = f"http://127.0.0.1:{srv.server_port}"
    try:
        f0 = compiled.stats["fault_compile"]
        with faults.inject("compile:1:sleep=60000"):
            payload = _post(f"{base}/v1/statement", QUERY)
            uid = payload["id"]
            _wait(lambda: compiled.stats["fault_compile"] > f0,
                  what="worker inside the stalled compile")
            fut = srv.app_state.future_list[uid]
            t0 = time.monotonic()
            srv.drain_async("test")
            assert srv.drained_event.wait(timeout=15)
            assert time.monotonic() - t0 < 10.0
            exc = fut.exception(timeout=5)
        from dask_sql_tpu.runtime import resilience as R
        assert isinstance(exc, R.QueryCancelled)
    finally:
        sched.get_manager().end_drain()
        try:
            srv.shutdown()
            srv.server_close()
        except Exception:
            pass
