"""Presto-protocol server tests (reference: tests/integration/test_server.py —
route codes, async polling loop, cancellation, error shape)."""
import json
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest


@pytest.fixture(scope="module")
def server():
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table("df", pd.DataFrame({"a": [1, 2, 3], "b": list("xyz")}))
    srv = run_server(context=context, host="127.0.0.1", port=0, blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _post(url, body):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def _run_to_completion(server, sql, timeout=30):
    payload = _post(f"{server}/v1/statement", sql)
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        payload = _get(payload["nextUri"])
    return payload


def test_empty(server):
    payload = _get(f"{server}/v1/empty")
    assert payload["columns"] == [] and payload["data"] == []


def test_query(server):
    payload = _run_to_completion(server, "SELECT * FROM df ORDER BY a")
    assert [c["name"] for c in payload["columns"]] == ["a", "b"]
    assert [c["type"] for c in payload["columns"]] == ["bigint", "varchar"]
    assert payload["data"] == [[1, "x"], [2, "y"], [3, "z"]]
    assert payload["stats"]["state"] == "FINISHED"


def test_error_shape(server):
    payload = _run_to_completion(server, "SELECT * FROM missing_table")
    assert "error" in payload
    # reference QueryError: errorName = str(type(error)) (responses.py:126)
    assert "ValidationException" in payload["error"]["errorName"]
    assert "errorLocation" in payload["error"]


def test_unknown_id(server):
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(f"{server}/v1/status/nope")
    assert exc.value.code == 404


def test_cancel(server):
    payload = _post(f"{server}/v1/statement", "SELECT 1 + 1")
    cancel = payload["partialCancelUri"]
    req = urllib.request.Request(cancel, method="DELETE")
    with urllib.request.urlopen(req) as r:
        assert r.status == 200
    # the id is gone afterwards
    with pytest.raises(urllib.error.HTTPError):
        _get(payload["nextUri"])


def test_aggregate_via_server(server):
    payload = _run_to_completion(server, "SELECT SUM(a) AS s FROM df")
    assert payload["data"] == [[6]]


def test_stats_filled(server):
    """The reference returns hardcoded zero stats (responses.py:11-49);
    ours must carry real execution telemetry (VERDICT r1 item 7)."""
    payload = _run_to_completion(server, "SELECT a, COUNT(*) AS n FROM df "
                                         "GROUP BY a")
    stats = payload["stats"]
    assert stats["state"] == "FINISHED"
    assert stats["processedRows"] == 3
    assert stats["processedBytes"] > 0
    assert stats["elapsedTimeMillis"] >= stats["wallTimeMillis"] >= 0
    assert stats["cpuTimeMillis"] >= 0
    # compile/cache split is present and consistent: the query ran through
    # the compiled pipeline exactly once (either fresh compile or hit)
    assert stats["compiledPrograms"] + stats["programCacheHits"] >= 1


def test_column_shape_matches_reference(server):
    """Field-by-field column description shape the reference's server test
    pins (/root/reference/tests/integration/test_server.py:50-57 and
    responses.py:67-77): name + lowercase type + typeSignature with
    rawType and empty arguments."""
    payload = _run_to_completion(server, "SELECT 1 + 1 AS x")
    assert payload["columns"] == [{
        "name": "x", "type": "integer",
        "typeSignature": {"rawType": "integer", "arguments": []},
    }]
    assert payload["data"] == [[2]]
    assert "error" not in payload
    assert "nextUri" not in payload

    payload = _run_to_completion(
        server, "SELECT a, b, a * 0.5 AS h FROM df ORDER BY a")
    shapes = [(c["name"], c["type"], c["typeSignature"]["rawType"],
               c["typeSignature"]["arguments"]) for c in payload["columns"]]
    assert shapes == [("a", "bigint", "bigint", []),
                      ("b", "varchar", "varchar", []),
                      ("h", "double", "double", [])]


def _get_metrics(server):
    with urllib.request.urlopen(f"{server}/metrics") as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def _metric_value(text, name):
    for line in text.splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"metric {name} not in /metrics output")


def test_metrics_endpoint_content_type_and_counters(server):
    """GET /metrics: prometheus text exposition of the telemetry registry
    — the counters previously only reachable via physical.compiled.stats."""
    status, ctype, text = _get_metrics(server)
    assert status == 200
    assert ctype.startswith("text/plain")
    assert "version=0.0.4" in ctype
    # the stable counter names export under the dsql_ prefix
    for name in ("dsql_compiles_total", "dsql_hits_total",
                 "dsql_fallbacks_total", "dsql_server_queries_total",
                 "dsql_queries_total"):
        assert f"# TYPE {name} counter" in text
        assert _metric_value(text, name) >= 0


def test_metrics_counters_are_monotonic(server):
    """Counters only move up: running a query strictly increases the
    server-query and engine-query counters and never decreases any."""
    _, _, before = _get_metrics(server)
    payload = _run_to_completion(server, "SELECT COUNT(*) AS n FROM df")
    assert payload["stats"]["state"] == "FINISHED"
    _, _, after = _get_metrics(server)
    assert (_metric_value(after, "dsql_server_queries_total")
            >= _metric_value(before, "dsql_server_queries_total") + 1)
    assert (_metric_value(after, "dsql_queries_total")
            >= _metric_value(before, "dsql_queries_total") + 1)
    for line in before.splitlines():
        if line.startswith("dsql_") and "_total " in line:
            name = line.split(" ")[0]
            assert _metric_value(after, name) >= _metric_value(before, name)


def test_metrics_histograms_present(server):
    _run_to_completion(server, "SELECT 1 + 1")
    _, _, text = _get_metrics(server)
    assert "# TYPE dsql_query_wall_ms histogram" in text
    assert 'dsql_query_wall_ms_bucket{le="+Inf"}' in text
    assert _metric_value(text, "dsql_query_wall_ms_count") >= 1


def test_stats_phase_breakdown(server):
    """Per-query wire stats carry the query's OWN phase split (from its
    thread-local QueryReport, not a racy process-global)."""
    payload = _run_to_completion(server, "SELECT SUM(a) AS s FROM df")
    phases = payload["stats"].get("phaseMillis")
    assert phases, "phaseMillis missing from finished-query stats"
    assert "parse" in phases and "execute" in phases
    assert all(v >= 0 for v in phases.values())


def test_error_location_matches_reference(server):
    """The reference asserts the exact parse position in errorLocation
    (test_server.py:60-74: 'SELECT 1 + ' -> line 1, column 10+); ours
    carries the native parser's 1-based position instead of a hardcoded
    1,1."""
    payload = _run_to_completion(server, "SELECT 1 + ")
    assert "columns" not in payload
    err = payload["error"]
    assert "message" in err
    loc = err["errorLocation"]
    assert loc["lineNumber"] == 1
    assert loc["columnNumber"] >= 10
    payload = _run_to_completion(server, "SELECT nope FROM df\nWHERE boom")
    # the binder reports the unresolvable column at line 1; a multi-line
    # position must survive to the wire (verified: line=1 col=8 for nope)
    loc2 = payload["error"]["errorLocation"]
    assert (loc2["lineNumber"], loc2["columnNumber"]) != (1, 1)
