"""SQL -> static-domain GROUP BY -> pallas MXU reduction, end-to-end.

DSQL_PALLAS=force routes the SUM/AVG/COUNT family through the one-hot
matmul kernel in interpreter mode on CPU (natively on TPU); results are
compared against pandas.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context


@pytest.fixture()
def li_ctx():
    rng = np.random.RandomState(0)
    n = 3000
    df = pd.DataFrame({
        "rf": rng.choice(["A", "N", "R"], n),
        "ls": rng.choice(["O", "F"], n),
        "qty": rng.rand(n) * 50,
        "price": rng.rand(n) * 1000,
        "disc": rng.rand(n) * 0.1,
    })
    ctx = Context()
    ctx.create_table("li", df)
    return ctx, df


def test_q1_shape_through_pallas(li_ctx, monkeypatch):
    monkeypatch.setenv("DSQL_PALLAS", "force")
    ctx, df = li_ctx
    r = ctx.sql(
        "SELECT rf, ls, SUM(qty) AS sq, SUM(price) AS sp, AVG(disc) AS ad, "
        "COUNT(*) AS n FROM li WHERE qty < 40 GROUP BY rf, ls ORDER BY rf, ls",
        return_futures=False)
    exp = (df[df.qty < 40].groupby(["rf", "ls"])
           .agg(sq=("qty", "sum"), sp=("price", "sum"), ad=("disc", "mean"),
                n=("qty", "count"))
           .reset_index().sort_values(["rf", "ls"], ignore_index=True))
    pd.testing.assert_frame_equal(r.reset_index(drop=True), exp,
                                  check_dtype=False, rtol=1e-10)


def test_static_domain_with_nulls(monkeypatch):
    monkeypatch.setenv("DSQL_PALLAS", "force")
    ctx = Context()
    df = pd.DataFrame({"k": ["a", None, "b", "a", None, "b", "a"],
                       "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]})
    ctx.create_table("t", df)
    r = ctx.sql("SELECT k, SUM(v) AS s, COUNT(v) AS n FROM t GROUP BY k",
                return_futures=False)
    r = r.sort_values("k", na_position="first", ignore_index=True)
    assert r["s"].tolist() == [7.0, 12.0, 9.0]
    assert r["n"].tolist() == [2, 3, 2]
    assert pd.isna(r["k"][0])
