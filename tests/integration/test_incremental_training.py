"""Out-of-core (incremental) model training over chunked tables.

Reference semantics: CREATE MODEL (wrap_fit = True) streams training through
partial_fit partition-by-partition via dask-ml Incremental
(/root/reference/dask_sql/physical/rel/custom/create_model.py:141-155);
wrap_predict gives partitioned prediction (:147-155).
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context


def _training_frame(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = (x1 + 0.5 * x2 + rng.normal(scale=0.3, size=n) > 0).astype(np.int64)
    return pd.DataFrame({"x1": x1, "x2": x2, "target": y})


def test_wrap_fit_streams_partial_fit_batches(monkeypatch):
    """Training over a chunked table must stream partial_fit per batch and
    never gather the full table through the resident executor."""
    from sklearn.linear_model import SGDClassifier

    calls = {"partial_fit": 0, "fit": 0, "max_rows": 0}
    orig_pf = SGDClassifier.partial_fit
    orig_fit = SGDClassifier.fit

    def counting_pf(self, X, y=None, **kw):
        calls["partial_fit"] += 1
        calls["max_rows"] = max(calls["max_rows"], len(X))
        return orig_pf(self, X, y, **kw)

    def counting_fit(self, *a, **kw):
        calls["fit"] += 1
        return orig_fit(self, *a, **kw)

    monkeypatch.setattr(SGDClassifier, "partial_fit", counting_pf)
    monkeypatch.setattr(SGDClassifier, "fit", counting_fit)

    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=1000)
    c.sql("""
        CREATE MODEL my_model WITH (
            model_class = 'sklearn.linear_model.SGDClassifier',
            wrap_fit = True,
            target_column = 'target',
            loss = 'log_loss',
            random_state = 0
        ) AS SELECT x1, x2, target FROM timeseries
    """)
    assert calls["fit"] == 0, "wrap_fit must not gather-and-fit"
    assert calls["partial_fit"] == 4, "one partial_fit per 1000-row batch"
    assert calls["max_rows"] <= 1000, \
        "a single partial_fit call saw more than one batch"

    model, columns = c.schema[c.schema_name].models["my_model"]
    assert columns == ["x1", "x2"]
    # the streamed model must actually have learned the separating plane
    acc = (model.predict(df[["x1", "x2"]].to_numpy())
           == df["target"].to_numpy()).mean()
    assert acc > 0.9


def test_wrap_fit_classifier_prescans_classes():
    """Labels appearing only in LATE batches must reach the first
    partial_fit call (the classes prescan)."""
    n = 3000
    rng = np.random.default_rng(0)
    df = pd.DataFrame({
        "x1": rng.normal(size=n),
        # class 2 exists only in the last third of the rows
        "target": np.repeat([0, 1, 2], n // 3),
    })
    c = Context()
    c.create_table("t", df, chunked=True, batch_rows=500)
    c.sql("""
        CREATE MODEL m3 WITH (
            model_class = 'sklearn.linear_model.SGDClassifier',
            wrap_fit = True,
            target_column = 'target',
            random_state = 0
        ) AS SELECT x1, target FROM t
    """)
    model, _ = c.schema[c.schema_name].models["m3"]
    assert sorted(model.classes_.tolist()) == [0, 1, 2]


def test_wrap_fit_streams_through_projection_and_filter():
    """Row-local plan shapes (expressions, WHERE) stream per batch."""
    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=512)
    c.sql("""
        CREATE MODEL m2 WITH (
            model_class = 'sklearn.linear_model.SGDRegressor',
            wrap_fit = True,
            target_column = 'target',
            random_state = 0
        ) AS SELECT x1 * 2 AS a, x2 + 1 AS b, target
             FROM timeseries WHERE x1 > -10
    """)
    model, columns = c.schema[c.schema_name].models["m2"]
    assert columns == ["a", "b"]
    assert hasattr(model, "coef_")


def test_wrap_fit_blocking_plan_is_loud():
    """An aggregate above the chunked scan is not a row-stream: the engine
    must refuse rather than train on silently-wrong data."""
    from dask_sql_tpu.physical.streaming import StreamingUnsupported

    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=1000)
    with pytest.raises(StreamingUnsupported):
        c.sql("""
            CREATE MODEL mbad WITH (
                model_class = 'sklearn.linear_model.SGDClassifier',
                wrap_fit = True,
                target_column = 'target'
            ) AS SELECT x1, MAX(x2) AS x2, MAX(target) AS target
                 FROM timeseries GROUP BY x1
        """)


def test_wrap_fit_without_partial_fit_is_loud():
    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=1000)
    with pytest.raises(AttributeError, match="partial_fit"):
        c.sql("""
            CREATE MODEL mbad2 WITH (
                model_class = 'sklearn.tree.DecisionTreeClassifier',
                wrap_fit = True,
                target_column = 'target'
            ) AS SELECT x1, x2, target FROM timeseries
        """)


def test_wrap_predict_batches_prediction():
    """wrap_predict wraps the estimator so predict runs in bounded slices
    (ParallelPostFit analogue) and composes with SQL PREDICT."""
    from dask_sql_tpu.models.incremental import BatchedPredictor

    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=1000)
    c.sql("""
        CREATE MODEL mp WITH (
            model_class = 'sklearn.linear_model.SGDClassifier',
            wrap_fit = True,
            wrap_predict = True,
            target_column = 'target',
            random_state = 0
        ) AS SELECT x1, x2, target FROM timeseries
    """)
    model, _ = c.schema[c.schema_name].models["mp"]
    assert isinstance(model, BatchedPredictor)

    # slice boundaries must not change predictions
    X = df[["x1", "x2"]].to_numpy()
    full = np.asarray(model.model.predict(X))
    model.batch_rows = 300
    sliced = model.predict(X)
    np.testing.assert_array_equal(full, sliced)

    # and SQL PREDICT over a RESIDENT source goes through the wrapper
    c.create_table("resident", df.head(100))
    out = c.sql(
        "SELECT * FROM PREDICT(MODEL mp, SELECT x1, x2 FROM resident)",
        return_futures=False)
    assert len(out) == 100


def test_gathered_create_model_over_chunked_is_correct():
    """WITHOUT wrap_fit, CREATE MODEL over a chunked source must still see
    the REAL rows (not the 1-row binding stub) — it routes through the
    streaming executor or fails loudly, never trains on wrong data."""
    from dask_sql_tpu.physical.streaming import StreamingUnsupported

    df = _training_frame()
    c = Context()
    c.create_table("timeseries", df, chunked=True, batch_rows=1000)
    # a plain row-stream SELECT has no aggregate/limit: the streaming
    # executor refuses (result as large as the table) — loud, never wrong
    with pytest.raises(StreamingUnsupported):
        c.sql("""
            CREATE MODEL mg WITH (
                model_class = 'sklearn.linear_model.SGDClassifier',
                target_column = 'target'
            ) AS SELECT x1, x2, target FROM timeseries
        """)
