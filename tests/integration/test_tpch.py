"""All 22 TPC-H queries against the SQLite differential oracle.

The reference never ships TPC-H; its oracle strategy (SURVEY §4) is applied
here to the benchmark workload itself: tiny-scale-factor generated data runs
through the engine and through SQLite, modulo dialect rewrites SQLite needs
(DATE literals, SUBSTRING FROM/FOR, EXTRACT(YEAR ...)). Dates load into
SQLite as ISO strings so comparisons behave like dates.
"""
import re
import sqlite3

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context

SF = 0.003


@pytest.fixture(scope="module")
def tpch():
    data = generate_tpch(SF)
    ctx = Context()
    conn = sqlite3.connect(":memory:")
    for name, df in data.items():
        ctx.create_table(name, df)
        sdf = df.copy()
        for col in sdf.columns:
            if sdf[col].dtype.kind == "M":
                sdf[col] = sdf[col].dt.strftime("%Y-%m-%d")
        sdf.to_sql(name, conn, index=False)
    yield ctx, conn
    conn.close()


def _to_sqlite(q: str) -> str:
    q = q.replace("DATE '", "'")
    q = re.sub(r"SUBSTRING\(\s*(\w+)\s+FROM\s+(\d+)\s+FOR\s+(\d+)\s*\)",
               r"substr(\1, \2, \3)", q)
    q = re.sub(r"EXTRACT\(\s*YEAR\s+FROM\s+(\w+)\s*\)",
               r"CAST(strftime('%Y', \1) AS INTEGER)", q)
    return q


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_query_matches_sqlite(tpch, qid):
    ctx, conn = tpch
    q = QUERIES[qid]
    got = ctx.sql(q, return_futures=False)
    want = pd.read_sql(_to_sqlite(q), conn)
    got = got.reset_index(drop=True)
    want = want.reset_index(drop=True)
    got.columns = [c.lower() for c in got.columns]
    want.columns = [c.lower() for c in want.columns]
    assert len(got) == len(want), f"Q{qid}: {len(got)} vs {len(want)} rows"
    ordered = "ORDER BY" in q
    if not ordered:
        key = list(got.columns)
        got = got.sort_values(key, ignore_index=True)
        want = want.sort_values(key, ignore_index=True)
    for col in want.columns:
        gv, wv = got[col], want[col]
        if gv.dtype.kind == "M":
            gv = gv.dt.strftime("%Y-%m-%d")
        if gv.dtype.kind in "fc" or wv.dtype.kind in "fc":
            np.testing.assert_allclose(
                pd.to_numeric(gv, errors="coerce").to_numpy(dtype=float),
                pd.to_numeric(wv, errors="coerce").to_numpy(dtype=float),
                rtol=1e-6, err_msg=f"Q{qid} col {col}")
        else:
            assert (gv.astype(str).to_numpy()
                    == wv.astype(str).to_numpy()).all(), f"Q{qid} col {col}"
