"""All 22 TPC-H queries against the SQLite differential oracle.

The reference never ships TPC-H; its oracle strategy (SURVEY §4) is applied
here to the benchmark workload itself: tiny-scale-factor generated data runs
through the engine and through SQLite, modulo dialect rewrites SQLite needs
(DATE literals, SUBSTRING FROM/FOR, EXTRACT(YEAR ...)). Dates load into
SQLite as ISO strings so comparisons behave like dates.
"""
import re
import sqlite3

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context
from tests.conftest import needs_compiled

SF = 0.003


@pytest.fixture(scope="module")
def tpch_data():
    return generate_tpch(SF)


@pytest.fixture(scope="module")
def tpch(tpch_data):
    data = tpch_data
    ctx = Context()
    conn = sqlite3.connect(":memory:")
    for name, df in data.items():
        ctx.create_table(name, df)
        sdf = df.copy()
        for col in sdf.columns:
            if sdf[col].dtype.kind == "M":
                sdf[col] = sdf[col].dt.strftime("%Y-%m-%d")
        sdf.to_sql(name, conn, index=False)
    yield ctx, conn
    conn.close()


def _to_sqlite(q: str) -> str:
    q = q.replace("DATE '", "'")
    q = re.sub(r"SUBSTRING\(\s*(\w+)\s+FROM\s+(\d+)\s+FOR\s+(\d+)\s*\)",
               r"substr(\1, \2, \3)", q)
    q = re.sub(r"EXTRACT\(\s*YEAR\s+FROM\s+(\w+)\s*\)",
               r"CAST(strftime('%Y', \1) AS INTEGER)", q)
    return q


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_query_matches_sqlite(tpch, qid):
    ctx, conn = tpch
    q = QUERIES[qid]
    got = ctx.sql(q, return_futures=False)
    want = pd.read_sql(_to_sqlite(q), conn)
    got = got.reset_index(drop=True)
    want = want.reset_index(drop=True)
    got.columns = [c.lower() for c in got.columns]
    want.columns = [c.lower() for c in want.columns]
    assert len(got) == len(want), f"Q{qid}: {len(got)} vs {len(want)} rows"
    ordered = "ORDER BY" in q
    if not ordered:
        key = list(got.columns)
        got = got.sort_values(key, ignore_index=True)
        want = want.sort_values(key, ignore_index=True)
    for col in want.columns:
        gv, wv = got[col], want[col]
        if gv.dtype.kind == "M":
            gv = gv.dt.strftime("%Y-%m-%d")
        if gv.dtype.kind in "fc" or wv.dtype.kind in "fc":
            np.testing.assert_allclose(
                pd.to_numeric(gv, errors="coerce").to_numpy(dtype=float),
                pd.to_numeric(wv, errors="coerce").to_numpy(dtype=float),
                rtol=1e-6, err_msg=f"Q{qid} col {col}")
        else:
            assert (gv.astype(str).to_numpy()
                    == wv.astype(str).to_numpy()).all(), f"Q{qid} col {col}"


@needs_compiled
@pytest.mark.parametrize("force_tpu", [False, True],
                         ids=["native-cpu", "forced-tpu"])
def test_all_queries_use_compiled_path(tpch_data, monkeypatch, force_tpu):
    """Every TPC-H query must run as ONE compiled program, no eager
    fallbacks — certified on BOTH strategies: the native platform's
    (hash join / hash groupby on this CPU test host — the path the
    driver's bench records on fallback) and the forced-TPU merge-join
    path. A fresh Context is load-bearing: the program cache keys on
    table identity, so reusing the oracle fixture's tables could replay
    programs traced before the monkeypatch."""
    from dask_sql_tpu.ops import pallas_kernels
    from dask_sql_tpu.physical import compiled
    # pin the strategy explicitly: an ambient DSQL_STRATEGY would otherwise
    # make both variants certify the same path
    monkeypatch.delenv("DSQL_STRATEGY", raising=False)
    if force_tpu:
        monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
    data = tpch_data
    ctx = Context()
    for name, df in data.items():
        ctx.create_table(name, df)
    not_compiled = []
    for qid in sorted(QUERIES):
        s0 = dict(compiled.stats)
        ctx.sql(QUERIES[qid], return_futures=False)
        d = {k: compiled.stats[k] - s0[k] for k in s0}
        if not (d["hits"] or d["compiles"]) or d["fallbacks"] or d["unsupported"]:
            not_compiled.append((qid, d))
    assert not not_compiled, f"queries off the compiled path: {not_compiled}"
