"""Window function tests (reference: tests/integration/test_over.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_over_with_sorting(c, user_table_1):
    result = c.sql(
        """SELECT user_id, b,
                  ROW_NUMBER() OVER (ORDER BY user_id, b) AS "R"
           FROM user_table_1""")
    expected = user_table_1.copy()
    expected["R"] = (user_table_1.sort_values(["user_id", "b"]).index.argsort() + 1)
    expected["R"] = user_table_1.assign(
        _r=np.argsort(np.lexsort((user_table_1["b"], user_table_1["user_id"]))) + 1
    )["_r"]
    assert_eq(result, expected)


def test_over_with_partitioning(c, user_table_2):
    result = c.sql(
        """SELECT user_id, c,
                  ROW_NUMBER() OVER (PARTITION BY c ORDER BY user_id) AS "R"
           FROM user_table_2""")
    expected = user_table_2.copy()
    expected["R"] = user_table_2.groupby("c")["user_id"].rank(method="first").astype(int)
    assert_eq(result, expected)


def test_over_with_grouping_and_sort(c, user_table_1):
    result = c.sql(
        """SELECT user_id, b,
                  ROW_NUMBER() OVER (PARTITION BY user_id ORDER BY b) AS "R"
           FROM user_table_1""")
    expected = user_table_1.copy()
    expected["R"] = user_table_1.groupby("user_id")["b"].rank(method="first").astype(int)
    assert_eq(result, expected)


def test_over_with_different(c, user_table_1):
    result = c.sql(
        """SELECT user_id, b,
                  ROW_NUMBER() OVER (PARTITION BY user_id ORDER BY b) AS "R1",
                  ROW_NUMBER() OVER (ORDER BY user_id, b) AS "R2"
           FROM user_table_1""").to_pandas()
    expected = user_table_1.copy()
    expected["R1"] = user_table_1.groupby("user_id")["b"].rank(method="first").astype(int)
    expected["R2"] = np.argsort(np.lexsort((user_table_1["b"], user_table_1["user_id"]))) + 1
    assert_eq(result, expected)


def test_over_calls(c, user_table_1):
    result = c.sql(
        """SELECT user_id, b,
            FIRST_VALUE(user_id*10 - b) OVER (PARTITION BY user_id ORDER BY b) AS "F",
            SUM(b) OVER (PARTITION BY user_id ORDER BY b) AS "S",
            AVG(b) OVER (PARTITION BY user_id ORDER BY b) AS "A",
            COUNT(*) OVER (PARTITION BY user_id ORDER BY b) AS "C",
            MAX(b) OVER (PARTITION BY user_id ORDER BY b) AS "M"
           FROM user_table_1""").to_pandas()
    df2 = user_table_1.sort_values(["user_id", "b"]).copy()
    g = df2.groupby("user_id")
    first_vals = (df2["user_id"] * 10 - df2["b"]).groupby(df2["user_id"]).transform("first")
    df2["F"] = first_vals
    df2["S"] = g["b"].cumsum()
    df2["A"] = g["b"].expanding().mean().reset_index(level=0, drop=True)
    df2["C"] = g.cumcount() + 1
    df2["M"] = g["b"].cummax()
    expected = df2.loc[user_table_1.index].reset_index(drop=True)
    assert_eq(result, expected[["user_id", "b", "F", "S", "A", "C", "M"]])


def test_over_with_windows(c):
    frame = pd.DataFrame({"a": range(5)})
    c.create_table("tmp", frame)
    result = c.sql(
        """SELECT a,
            SUM(a) OVER (ORDER BY a ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS "S1",
            SUM(a) OVER (ORDER BY a ROWS BETWEEN 2 PRECEDING AND 1 FOLLOWING) AS "S2",
            SUM(a) OVER (ORDER BY a ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS "S3",
            SUM(a) OVER (ORDER BY a ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) AS "S4"
           FROM tmp""").to_pandas()
    a = frame["a"]
    assert list(result["S1"]) == list(a.rolling(3, min_periods=1).sum().astype(int))
    expected_s2 = [a[max(0, i - 2): i + 2].sum() for i in range(5)]
    assert list(result["S2"]) == expected_s2
    assert list(result["S3"]) == list(a.cumsum())
    assert list(result["S4"]) == [a.sum()] * 5


def test_rank_functions(c, user_table_1):
    result = c.sql(
        """SELECT user_id, b,
                  RANK() OVER (PARTITION BY user_id ORDER BY b) AS "r",
                  DENSE_RANK() OVER (PARTITION BY user_id ORDER BY b) AS "dr"
           FROM user_table_1""").to_pandas()
    df = user_table_1
    expected_r = df.groupby("user_id")["b"].rank(method="min").astype(int)
    expected_dr = df.groupby("user_id")["b"].rank(method="dense").astype(int)
    assert list(result["r"]) == list(expected_r)
    assert list(result["dr"]) == list(expected_dr)


def test_lag_lead(c):
    frame = pd.DataFrame({"g": [1, 1, 1, 2, 2], "v": [10, 20, 30, 40, 50]})
    c.create_table("ll", frame)
    result = c.sql(
        """SELECT g, v,
                  LAG(v) OVER (PARTITION BY g ORDER BY v) AS "lag1",
                  LEAD(v) OVER (PARTITION BY g ORDER BY v) AS "lead1"
           FROM ll""").to_pandas()
    assert list(result["lag1"].fillna(-1)) == [-1, 10, 20, -1, 40]
    assert list(result["lead1"].fillna(-1)) == [20, 30, -1, 50, -1]


def test_unbounded_preceding_to_following_minmax(c):
    """One-side-unbounded MIN/MAX frames use scan+gather, not a shift loop;
    aggregate a DIFFERENT column than the order key so the bounded offset
    actually matters."""
    import pandas as pd
    c.create_table("wf_t", pd.DataFrame({"o": [1, 2, 3, 4],
                                         "v": [5.0, 1.0, 7.0, 3.0]}))
    r = c.sql(
        "SELECT o, MIN(v) OVER (ORDER BY o ROWS BETWEEN UNBOUNDED PRECEDING "
        "AND 1 FOLLOWING) AS m1, "
        "MAX(v) OVER (ORDER BY o ROWS BETWEEN 1 PRECEDING AND UNBOUNDED "
        "FOLLOWING) AS m2 FROM wf_t ORDER BY o", return_futures=False)
    assert r["m1"].tolist() == [1.0, 1.0, 1.0, 1.0]
    assert r["m2"].tolist() == [7.0, 7.0, 7.0, 7.0]
    r2 = c.sql(
        "SELECT o, MIN(v) OVER (ORDER BY o ROWS BETWEEN UNBOUNDED PRECEDING "
        "AND 1 PRECEDING) AS m FROM wf_t ORDER BY o", return_futures=False)
    # first row's frame is empty -> NULL
    import numpy as np
    assert np.isnan(r2["m"].iloc[0])
    assert r2["m"].tolist()[1:] == [5.0, 1.0, 1.0]


def test_bounded_minmax_frames_vs_bruteforce(c):
    """van Herk sliding MIN/MAX vs brute force over random data, partitions,
    and frame shapes (incl. frames clipped at segment edges)."""
    import numpy as np
    import pandas as pd
    rng = np.random.RandomState(42)
    n = 200
    df = pd.DataFrame({"p": rng.randint(0, 5, n),
                       "o": rng.permutation(n),
                       "v": rng.randn(n).round(3)})
    c.create_table("vh_t", df)
    for lo, hi in ((-2, 1), (-7, -3), (2, 9), (-4, 0), (0, 4)):
        lo_s = f"{-lo} PRECEDING" if lo < 0 else (
            "CURRENT ROW" if lo == 0 else f"{lo} FOLLOWING")
        hi_s = f"{-hi} PRECEDING" if hi < 0 else (
            "CURRENT ROW" if hi == 0 else f"{hi} FOLLOWING")
        q = (f"SELECT p, o, v, MIN(v) OVER (PARTITION BY p ORDER BY o "
             f"ROWS BETWEEN {lo_s} AND {hi_s}) AS mn, "
             f"MAX(v) OVER (PARTITION BY p ORDER BY o "
             f"ROWS BETWEEN {lo_s} AND {hi_s}) AS mx FROM vh_t")
        r = c.sql(q, return_futures=False).sort_values(["p", "o"],
                                                       ignore_index=True)
        for p in range(5):
            grp = df[df.p == p].sort_values("o").reset_index(drop=True)
            got = r[r.p == p].reset_index(drop=True)
            for i in range(len(grp)):
                window = grp.v.iloc[max(i + lo, 0): max(i + hi + 1, 0)]
                if len(window):
                    assert got.mn[i] == window.min(), (lo, hi, p, i)
                    assert got.mx[i] == window.max(), (lo, hi, p, i)
                else:
                    assert pd.isna(got.mn[i]), (lo, hi, p, i)


def test_window_tpu_sort_payload_branch(c, user_table_1, monkeypatch):
    # force the TPU payload-through-sort branch of compute_window off-TPU:
    # same results must come out of both backends' sort/unsort strategies.
    # DSQL_COMPILE=0 keeps both runs on the eager path — the compiled-plan
    # cache would otherwise replay the first run's program for the second
    monkeypatch.setenv("DSQL_COMPILE", "0")
    from dask_sql_tpu.ops import pallas_kernels
    q = ("SELECT user_id, b, "
         "SUM(b) OVER (PARTITION BY user_id ORDER BY b) AS s, "
         "RANK() OVER (PARTITION BY user_id ORDER BY b) AS r "
         "FROM user_table_1")
    base = c.sql(q, return_futures=False).sort_values(
        ["user_id", "b"], ignore_index=True)
    monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
    forced = c.sql(q, return_futures=False).sort_values(
        ["user_id", "b"], ignore_index=True)
    pd.testing.assert_frame_equal(base, forced)
