"""GET /v1/engine: the one-poll live engine snapshot (server/app.py
_engine_snapshot) — payload shape, counters, and a mid-flight query."""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_HISTORY_FILE", str(tmp_path / "hist.jsonl"))
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table("t", {"a": np.arange(8, dtype=np.int64)})
    release = threading.Event()

    def slow_fn(x):
        release.set()
        time.sleep(1.5)
        return x.astype(np.float64)

    context.register_function(slow_fn, "slow_fn", [("x", np.int64)],
                              np.float64)
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}", release
    srv.shutdown()


def _get(url):
    with urllib.request.urlopen(url) as r:
        return json.loads(r.read())


def _post(url, body):
    req = urllib.request.Request(url, data=body.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read())


def test_engine_snapshot_shape(server):
    base, _ = server
    snap = _get(f"{base}/v1/engine")
    for key in ("pid", "active", "serverQueries", "scheduler", "memory",
                "cache", "quarantine", "programStore",
                "backgroundCompiles", "history"):
        assert key in snap, key
    assert snap["history"]["enabled"] is True
    assert snap["history"]["file"].endswith("hist.jsonl")
    sched = snap["scheduler"]
    assert {"enabled", "limit", "queueDepth", "running", "waiting",
            "draining"} <= set(sched)
    assert {"budgetBytes", "reservedBytes"} <= set(snap["memory"])
    assert {"entries", "device_bytes", "host_bytes"} <= set(snap["cache"])


def test_engine_reports_query_mid_flight(server):
    base, release = server
    payload = _post(f"{base}/v1/statement",
                    "SELECT SUM(slow_fn(a)) AS s FROM t")
    assert release.wait(timeout=60), "UDF never started"
    snap = _get(f"{base}/v1/engine")
    live = [a for a in snap["active"] if "slow_fn" in a["query"]]
    assert live, f"mid-flight query missing from snapshot: {snap['active']}"
    assert live[0]["elapsedMillis"] >= 0
    assert any(q["state"] in ("RUNNING", "QUEUED")
               for q in snap["serverQueries"])
    # drain the query so the server fixture can shut down cleanly
    deadline = time.time() + 60
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        payload = _get(payload["nextUri"])
    assert payload["data"] == [[28.0]]
    snap = _get(f"{base}/v1/engine")
    assert not any("slow_fn" in a["query"] for a in snap["active"])
    # the finished query is in the persistent history now
    assert snap["history"]["records"] >= 1
