"""PostgreSQL-class differential coverage (randomized, seeded).

The reference double-oracles the tricky query classes against a dockerized
PostgreSQL (/root/reference/tests/integration/fixtures.py:188-288,
test_postgres.py:9-44) because SQLite's loose typing hides NULL-ordering,
decimal, interval and frame edge cases.  No docker exists in this image, so
these tests close the same classes two ways:

- sqlite3 >= 3.40 DOES implement window frames (ROWS/RANGE with offsets),
  ``NULLS FIRST/LAST`` on ORDER BY, and correlated subqueries with
  standard semantics — those classes stay differential (eq_sqlite);
- INTERVAL/date arithmetic and DECIMAL cast chains, where sqlite has no
  real types, are GOLDEN tests: expectations computed with pandas /
  python decimal following PostgreSQL semantics.
"""
import datetime

import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq, eq_sqlite, make_rand_df

from dask_sql_tpu import Context


# ---------------------------------------------------------------------------
# NULLS FIRST / NULLS LAST x ASC / DESC (reference: postgres sort tests)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("direction", ["ASC", "DESC"])
@pytest.mark.parametrize("nulls", ["FIRST", "LAST"])
def test_order_nulls_directions_rand(direction, nulls):
    a = make_rand_df(40, a=(int, 8), b=(float, 8), c=(str, 8))
    eq_sqlite(
        f"SELECT * FROM a ORDER BY a {direction} NULLS {nulls}, "
        f"b {direction} NULLS {nulls}, c LIMIT 25",
        check_row_order=True, a=a)


def test_order_mixed_nulls_directions_rand():
    a = make_rand_df(40, a=(int, 10), b=(float, 10))
    eq_sqlite(
        "SELECT * FROM a ORDER BY a ASC NULLS FIRST, b DESC NULLS LAST "
        "LIMIT 30", check_row_order=True, a=a)
    eq_sqlite(
        "SELECT * FROM a ORDER BY a DESC NULLS FIRST, b ASC NULLS LAST "
        "LIMIT 30", check_row_order=True, a=a)


# ---------------------------------------------------------------------------
# correlated EXISTS / NOT EXISTS / IN / NOT IN (reference: postgres
# correlated-subquery coverage the sqlite suite skipped)
# ---------------------------------------------------------------------------

def test_correlated_exists_rand():
    a = make_rand_df(30, k=(int, 5), va=float)
    b = make_rand_df(25, k=(int, 5), vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k)", a=a, b=b)


def test_correlated_not_exists_rand():
    a = make_rand_df(30, k=(int, 5), va=float)
    b = make_rand_df(25, k=(int, 5), vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE NOT EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k)", a=a, b=b)


def test_correlated_exists_with_condition_rand():
    a = make_rand_df(40, k=(int, 6), va=float)
    b = make_rand_df(30, k=(int, 6), vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE EXISTS "
        "(SELECT 1 FROM b WHERE b.k = a.k AND b.vb < a.va)", a=a, b=b)


def test_in_subquery_with_where_rand():
    a = make_rand_df(40, k=(int, 6), va=float)
    b = make_rand_df(30, k=(int, 6), vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE k IN (SELECT k FROM b WHERE vb > 3)",
        a=a, b=b)


def test_not_in_subquery_non_null_rand():
    # NOT IN over a null-free build side (the null-poisoned case is covered
    # by golden tests in test_semantics_oracle.py; sqlite agrees here)
    a = make_rand_df(40, k=int, va=float)
    b = make_rand_df(30, k=int, vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE k NOT IN (SELECT k FROM b WHERE vb > 2)",
        a=a, b=b)


def test_correlated_scalar_subquery_in_select_rand():
    # SELECT-list position: decorrelated to a LEFT join on the grouped
    # subplan (binder._decorrelate_select_subqueries, landed r4)
    a = make_rand_df(30, k=(int, 4), va=float)
    b = make_rand_df(40, k=(int, 4), vb=float)
    eq_sqlite(
        "SELECT k, va, (SELECT MAX(vb) FROM b WHERE b.k = a.k) AS mx "
        "FROM a", a=a, b=b)


def test_correlated_count_subquery_in_select():
    # COUNT over an empty correlated group is 0, not NULL (LEFT + COALESCE)
    a = pd.DataFrame({"k": [1, 2, 3, 4]})
    b = pd.DataFrame({"k": [1, 1, 3]})
    eq_sqlite(
        "SELECT k, (SELECT COUNT(*) FROM b WHERE b.k = a.k) AS n "
        "FROM a ORDER BY k", check_row_order=True, a=a, b=b)


def test_correlated_scalar_where_comparison_rand():
    a = make_rand_df(30, k=(int, 4), va=float)
    b = make_rand_df(40, k=(int, 4), vb=float)
    eq_sqlite(
        "SELECT k, va FROM a WHERE va > "
        "(SELECT AVG(vb) FROM b WHERE b.k = a.k)", a=a, b=b)


# ---------------------------------------------------------------------------
# window frames: explicit ROWS / RANGE bounds (reference: postgres window
# coverage; sqlite >= 3.28 implements the standard frame semantics)
# ---------------------------------------------------------------------------

def test_window_rows_unbounded_following_rand():
    a = make_rand_df(80, a=float, b=(int, 30), c=(str, 30))
    eq_sqlite(
        """
        SELECT a, b,
            SUM(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS s1,
            SUM(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                ROWS BETWEEN 1 FOLLOWING AND UNBOUNDED FOLLOWING) AS s2,
            MIN(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                ROWS BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS s3
        FROM a ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_default_frame_peers_rand():
    # ties under ORDER BY: the default frame is RANGE (peer-inclusive) —
    # the class postgres catches and row-based engines get wrong
    a = make_rand_df(100, a=(int, 20), b=(int, 30), c=(str, 20))
    eq_sqlite(
        """
        SELECT a, b, c,
            SUM(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST) AS s1,
            COUNT(*) OVER (ORDER BY a NULLS FIRST) AS s2,
            AVG(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST) AS s3
        FROM a ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_range_current_row_rand():
    a = make_rand_df(80, a=(int, 15), b=int, c=(str, 15))
    eq_sqlite(
        """
        SELECT a, b,
            SUM(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) AS s1,
            SUM(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                RANGE BETWEEN CURRENT ROW AND UNBOUNDED FOLLOWING) AS s2
        FROM a ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_range_value_offsets_rand():
    # RANGE <n> PRECEDING/FOLLOWING is VALUE-based (not row-based): needs a
    # single numeric non-null ORDER BY key, exactly postgres' rule
    a = make_rand_df(80, a=int, b=int, c=(str, 20))
    eq_sqlite(
        """
        SELECT a, b,
            SUM(b) OVER (PARTITION BY c ORDER BY a
                RANGE BETWEEN 2 PRECEDING AND CURRENT ROW) AS s1,
            COUNT(*) OVER (PARTITION BY c ORDER BY a
                RANGE BETWEEN 1 PRECEDING AND 3 FOLLOWING) AS s2,
            SUM(b) OVER (ORDER BY a
                RANGE BETWEEN CURRENT ROW AND 2 FOLLOWING) AS s3
        FROM a ORDER BY a, b NULLS FIRST, c NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_range_desc_value_offsets_rand():
    a = make_rand_df(60, a=int, b=int)
    eq_sqlite(
        """
        SELECT a, b,
            SUM(b) OVER (ORDER BY a DESC
                RANGE BETWEEN 2 PRECEDING AND CURRENT ROW) AS s1
        FROM a ORDER BY a, b
        """, check_row_order=True, a=a)


def test_window_first_last_value_frames_rand():
    a = make_rand_df(60, a=float, b=(int, 20), c=(str, 15))
    eq_sqlite(
        """
        SELECT a, b,
            FIRST_VALUE(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS f1,
            LAST_VALUE(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST
                ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS f2,
            LAST_VALUE(b) OVER (PARTITION BY c ORDER BY a NULLS FIRST) AS f3
        FROM a ORDER BY a NULLS FIRST, b NULLS FIRST, c NULLS FIRST
        """, check_row_order=True, a=a)


def test_window_last_value_default_frame_peers():
    # LAST_VALUE under the default frame returns the last PEER, not the
    # current row (sqlite + postgres agree; row-based engines return self)
    df = pd.DataFrame({"k": [1, 1, 2, 2, 3], "v": [10., 20., 30., 40., 50.]})
    eq_sqlite("SELECT k, v, LAST_VALUE(v) OVER (ORDER BY k) AS lv FROM t "
              "ORDER BY k, v", check_row_order=True, t=df)


# ---------------------------------------------------------------------------
# INTERVAL / date arithmetic — sqlite has no interval type, so these are
# GOLDEN tests with pandas-computed PostgreSQL-semantics expectations
# (reference: fixtures.py datetime_table postgres coverage)
# ---------------------------------------------------------------------------

@pytest.fixture()
def date_ctx():
    rng = np.random.RandomState(42)
    n = 60
    base = pd.Timestamp("1995-01-01")
    d = base + pd.to_timedelta(rng.randint(0, 1200, n), unit="D")
    df = pd.DataFrame({"d": d, "v": np.round(rng.rand(n) * 100, 2),
                       "i": rng.randint(0, 10, n)})
    ctx = Context()
    ctx.create_table("t", df)
    return ctx, df


def test_date_plus_interval_days(date_ctx):
    ctx, df = date_ctx
    got = ctx.sql("SELECT d + INTERVAL '90' DAY AS d2 FROM t",
                  return_futures=False)
    want = pd.DataFrame({"d2": df["d"] + pd.Timedelta(days=90)})
    assert_eq(got, want)


def test_date_minus_interval_filter(date_ctx):
    ctx, df = date_ctx
    got = ctx.sql(
        "SELECT COUNT(*) AS n FROM t WHERE d < DATE '1997-07-01' - "
        "INTERVAL '90' DAY", return_futures=False)
    lim = pd.Timestamp("1997-07-01") - pd.Timedelta(days=90)
    assert int(got["n"][0]) == int((df["d"] < lim).sum())


def test_date_interval_month_year(date_ctx):
    ctx, df = date_ctx
    got = ctx.sql(
        "SELECT COUNT(*) AS n FROM t WHERE d >= DATE '1995-06-15' + "
        "INTERVAL '3' MONTH AND d < DATE '1995-06-15' + INTERVAL '1' YEAR",
        return_futures=False)
    lo = pd.Timestamp("1995-09-15")
    hi = pd.Timestamp("1996-06-15")
    assert int(got["n"][0]) == int(((df["d"] >= lo) & (df["d"] < hi)).sum())


def test_extract_fields_grouping(date_ctx):
    ctx, df = date_ctx
    got = ctx.sql(
        "SELECT EXTRACT(YEAR FROM d) AS y, COUNT(*) AS n, SUM(v) AS s "
        "FROM t GROUP BY EXTRACT(YEAR FROM d) ORDER BY y",
        return_futures=False)
    want = (df.assign(y=df["d"].dt.year).groupby("y")
            .agg(n=("v", "size"), s=("v", "sum")).reset_index())
    assert_eq(got, want)


def test_date_difference_comparison(date_ctx):
    ctx, df = date_ctx
    # rows within 180 days of the minimum date
    got = ctx.sql(
        "SELECT COUNT(*) AS n FROM t WHERE d < (SELECT MIN(d) FROM t) + "
        "INTERVAL '180' DAY", return_futures=False)
    lim = df["d"].min() + pd.Timedelta(days=180)
    assert int(got["n"][0]) == int((df["d"] < lim).sum())


# ---------------------------------------------------------------------------
# DECIMAL cast chains — golden (sqlite's NUMERIC affinity cannot judge
# scale/rounding; postgres semantics: CAST rounds half-up at the target
# scale, arithmetic keeps exactness)
# ---------------------------------------------------------------------------

def test_decimal_cast_rounding():
    ctx = Context()
    ctx.create_table("t", pd.DataFrame(
        {"x": [1.004, 2.676, -1.004, 3.14159, 0.125]}))
    got = ctx.sql("SELECT CAST(x AS DECIMAL(10, 2)) AS d FROM t",
                  return_futures=False)
    # quantization at scale 2; exact halves round HALF-EVEN (0.125 -> 0.12)
    # — the engine's documented contract (physical/rex/cast.py:80-85),
    # matching the reference's pandas substrate where a true decimal
    # engine's half-up would give 0.13
    assert [round(v, 2) for v in got["d"]] == [1.0, 2.68, -1.0, 3.14, 0.12]


def test_decimal_chain_sum():
    rng = np.random.RandomState(9)
    cents = rng.randint(-10_000, 10_000, 200)
    df = pd.DataFrame({"x": cents / 100.0})
    ctx = Context()
    ctx.create_table("t", df)
    got = ctx.sql(
        "SELECT SUM(CAST(x AS DECIMAL(12, 2))) AS s, "
        "AVG(CAST(x AS DECIMAL(12, 2))) AS a FROM t",
        return_futures=False)
    # exact: the scaled-int representation must not lose cents
    assert abs(float(got["s"][0]) - cents.sum() / 100.0) < 1e-9
    assert abs(float(got["a"][0]) - cents.sum() / 100.0 / 200) < 1e-9


def test_decimal_cast_chain_widening():
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({"x": [1.115, 2.345, -0.555]}))
    got = ctx.sql(
        "SELECT CAST(CAST(x AS DECIMAL(10, 2)) AS DECIMAL(12, 1)) AS d "
        "FROM t", return_futures=False)
    # chain: 1.115 -> 1.12 -> 1.1 ; 2.345 -> 2.35 -> 2.4 (postgres:
    # each cast re-rounds at ITS scale) ; -0.555 -> -0.56 -> -0.6
    assert [round(v, 1) for v in got["d"]] == [1.1, 2.4, -0.6]


def test_decimal_multiply_precision():
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({"p": [19.99, 5.25, 100.01],
                                        "q": [3, 7, 2]}))
    got = ctx.sql(
        "SELECT SUM(CAST(p AS DECIMAL(10, 2)) * q) AS rev FROM t",
        return_futures=False)
    assert abs(float(got["rev"][0]) - (19.99 * 3 + 5.25 * 7 + 100.01 * 2)) \
        < 1e-9


def test_correlated_exists_nonequi_residual_int():
    """EXISTS with an equi key + integer non-equi residual — the shape the
    optimizer rewrites to a grouped MIN/MAX join (TPC-H Q21's; the in-join
    exist-test formulation OOM-killed the TPU compile helper).  Randomized
    against sqlite for <>, <, > in both SEMI and ANTI polarity."""
    a = make_rand_df(40, k=(int, 5), x=int, va=float)
    b = make_rand_df(50, k=(int, 5), x=int, vb=float)
    for op in ("<>", "<", ">", "<=", ">="):
        eq_sqlite(
            f"SELECT k, x, va FROM a WHERE EXISTS (SELECT 1 FROM b "
            f"WHERE b.k = a.k AND b.x {op} a.x)", a=a, b=b)
        eq_sqlite(
            f"SELECT k, x, va FROM a WHERE NOT EXISTS (SELECT 1 FROM b "
            f"WHERE b.k = a.k AND b.x {op} a.x)", a=a, b=b)


def test_correlated_exists_nonequi_all_null_build_group():
    # a build group whose x is entirely NULL can satisfy no comparison:
    # EXISTS false, NOT EXISTS keeps the row (COUNT(x)-guard in the
    # rewrite; sqlite agrees)
    a = pd.DataFrame({"k": [1, 2, 3], "x": [10, 20, 30]})
    b = pd.DataFrame({"k": [1, 1, 2],
                      "x": pd.array([None, None, 25], dtype="Int64")})
    eq_sqlite("SELECT k FROM a WHERE EXISTS (SELECT 1 FROM b "
              "WHERE b.k = a.k AND b.x <> a.x)", a=a, b=b)
    eq_sqlite("SELECT k FROM a WHERE NOT EXISTS (SELECT 1 FROM b "
              "WHERE b.k = a.k AND b.x <> a.x)", a=a, b=b)
