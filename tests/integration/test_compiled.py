"""Compiled-pipeline executor tests: equivalence with the eager path,
capacity escalation, runtime fallback, and plan caching.

The compiled executor (physical/compiled.py) traces whole plans into one
jitted program; these tests pin its semantics to the eager executor's over
the shared fixture catalog (conftest.py) — the same differential strategy the
reference uses between dask-sql and sqlite (test_compatibility.py:22-67).
"""
import os

import pandas as pd
import pytest

from dask_sql_tpu.physical import compiled


_needs_compiled = pytest.mark.skipif(
    os.environ.get("DSQL_COMPILE") == "0",
    reason="asserts compiled-path usage; meaningless with DSQL_COMPILE=0")


def _both_paths(c, query):
    """Run query compiled and eager; return (compiled_df, eager_df)."""
    comp = c.sql(query, return_futures=False)
    prev = os.environ.get("DSQL_COMPILE")
    os.environ["DSQL_COMPILE"] = "0"
    try:
        eager = c.sql(query, return_futures=False)
    finally:
        if prev is None:
            del os.environ["DSQL_COMPILE"]
        else:
            os.environ["DSQL_COMPILE"] = prev
    return comp, eager


def _assert_same(comp: pd.DataFrame, eager: pd.DataFrame, ordered: bool):
    if not ordered:
        cols = list(comp.columns)
        comp = comp.sort_values(cols, ignore_index=True)
        eager = eager.sort_values(cols, ignore_index=True)
    pd.testing.assert_frame_equal(comp.reset_index(drop=True),
                                  eager.reset_index(drop=True),
                                  check_dtype=False)


QUERIES = [
    ("SELECT * FROM df_simple", False),
    ("SELECT a + b AS s, a * b AS p FROM df_simple WHERE a > 1", False),
    ("SELECT a, SUM(b) AS sb, COUNT(*) AS n, AVG(b) AS ab FROM df GROUP BY a", False),
    ("SELECT a, SUM(b) FILTER (WHERE b > 5) AS sb FROM df GROUP BY a", False),
    ("SELECT SUM(b) AS sb, MIN(a) AS ma, MAX(b) AS mb FROM df", False),
    ("SELECT user_id, SUM(b) AS x FROM user_table_1 GROUP BY user_id "
     "HAVING SUM(b) > 2", False),
    ("SELECT * FROM df WHERE b BETWEEN 2 AND 6 ORDER BY b DESC LIMIT 7", True),
    ("SELECT * FROM df ORDER BY a ASC, b DESC LIMIT 5 OFFSET 3", True),
    ("SELECT u1.user_id, u2.c FROM user_table_1 u1 "
     "JOIN user_table_2 u2 ON u1.user_id = u2.user_id", False),
    ("SELECT u1.user_id, u2.c FROM user_table_1 u1 "
     "LEFT JOIN user_table_2 u2 ON u1.user_id = u2.user_id", False),
    ("SELECT user_id FROM user_table_1 WHERE user_id IN "
     "(SELECT user_id FROM user_table_2)", False),
    ("SELECT lk_nullint FROM user_table_lk WHERE lk_nullint IS NOT NULL", False),
    ("SELECT a FROM string_table WHERE a LIKE '%normal%'", False),
    ("SELECT user_id FROM user_table_1 UNION SELECT user_id FROM user_table_2",
     False),
    ("SELECT user_id FROM user_table_1 UNION ALL "
     "SELECT user_id FROM user_table_2", False),
    ("SELECT CASE WHEN a > 1 THEN b ELSE -b END AS x FROM df_simple", False),
    ("SELECT lk_nullint, COUNT(*) AS n FROM user_table_lk GROUP BY lk_nullint",
     False),
    ("SELECT c FROM user_table_nan WHERE c IS NOT NULL ORDER BY c", True),
]


@pytest.mark.parametrize("query,ordered", QUERIES)
def test_compiled_matches_eager(c, query, ordered):
    comp, eager = _both_paths(c, query)
    _assert_same(comp, eager, ordered)


@_needs_compiled
def test_compiled_path_used(c):
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    c.sql("SELECT a, SUM(b) AS s FROM df GROUP BY a")
    after = compiled.stats["compiles"] + compiled.stats["hits"]
    assert after == before + 1


@_needs_compiled
def test_left_join_actually_compiles(c):
    """LEFT joins must run compiled (guards against trace-breaking syncs in
    the masked-gather path). The build side needs UNIQUE keys: a duplicate
    build key (user_table_2 has one) is a legitimate runtime fallback, and
    this test must observe a clean compile-and-run, not that fallback."""
    c.create_table("lj_build", pd.DataFrame({"user_id": [1, 2, 4],
                                             "c": [10, 20, 40]}))
    before_uns = compiled.stats["unsupported"]
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    fb = compiled.stats["fallbacks"]
    c.sql("SELECT u1.user_id, u2.c FROM user_table_1 u1 "
          "LEFT JOIN lj_build u2 ON u1.user_id = u2.user_id")
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1
    assert compiled.stats["unsupported"] == before_uns
    assert compiled.stats["fallbacks"] == fb
    c.drop_table("lj_build")


@_needs_compiled
def test_cache_hit_on_repeat(c):
    q = "SELECT a, COUNT(*) AS n FROM df WHERE b < 9 GROUP BY a"
    c.sql(q)
    hits = compiled.stats["hits"]
    c.sql(q)
    assert compiled.stats["hits"] == hits + 1


@_needs_compiled
def test_group_capacity_escalation(c, monkeypatch):
    # force a tiny initial capacity: the first run overflows, the host
    # recompiles with a doubled capacity, the result is still exact
    monkeypatch.setattr(compiled, "DEFAULT_GROUP_CAP", 2)
    rec = compiled.stats["recompiles"]
    comp, eager = _both_paths(
        c, "SELECT b, COUNT(*) AS n FROM df GROUP BY b")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["recompiles"] > rec


@_needs_compiled
def test_group_caps_persist_to_file(c, monkeypatch, tmp_path):
    # DSQL_CAPS_FILE write-through: an escalation learned by this "process"
    # must be found by a cold one (simulated by clearing every in-memory
    # cache), so the first compile already uses the right capacity — on the
    # tunneled TPU a recompile costs 100-200 s
    caps_file = tmp_path / "caps.json"
    monkeypatch.setenv("DSQL_CAPS_FILE", str(caps_file))
    monkeypatch.setattr(compiled, "DEFAULT_GROUP_CAP", 2)
    monkeypatch.setattr(compiled, "_caps_disk", None)
    # distinct from the escalation test's query: the learned cap survives in
    # the restored in-memory dict after this test, and sharing a fingerprint
    # would rob that test of its recompile
    q = "SELECT b, SUM(a) AS s FROM df GROUP BY b"
    rec = compiled.stats["recompiles"]
    c.sql(q)
    assert compiled.stats["recompiles"] > rec
    assert caps_file.exists()
    # cold process: no programs, no in-memory caps — only the file
    monkeypatch.setattr(compiled, "_cache", type(compiled._cache)())
    monkeypatch.setattr(compiled, "_learned_caps",
                        type(compiled._learned_caps)())
    monkeypatch.setattr(compiled, "_caps_disk", None)
    rec = compiled.stats["recompiles"]
    comp, eager = _both_paths(c, q)
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["recompiles"] == rec


@_needs_compiled
def test_runtime_fallback_nonunique_build(c):
    # both sides have duplicate keys -> the unique-build invariant fails at
    # runtime; the flags vector reroutes to the eager executor, which handles
    # many-to-many joins
    fb = compiled.stats["fallbacks"]
    comp, eager = _both_paths(
        c, "SELECT u1.b, u2.b AS b2 FROM user_table_1 u1 "
           "JOIN user_table_1 u2 ON u1.user_id = u2.user_id")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["fallbacks"] > fb


@_needs_compiled
@pytest.mark.parametrize("strategy", ["merge", "gather"])
def test_semi_join_heavy_duplicate_build(c, strategy, monkeypatch):
    # a SEMI join build side with one key repeated 200x: duplicates are
    # legal for SEMI/ANTI and BOTH join strategies must handle them
    # in-program (merge: the carried build row has the same raw key;
    # gather: the leftmost equal-hash candidate does), with no runtime
    # fallback. The merge path is TPU-preferred, so force it explicitly —
    # off-TPU the default would quietly test only the gather path.
    import numpy as np
    from dask_sql_tpu.ops import pallas_kernels
    monkeypatch.setattr(pallas_kernels, "_on_tpu",
                        lambda: strategy == "merge")
    big = pd.DataFrame({"k": np.r_[np.full(200, 7), np.arange(50)].astype(np.int64)})
    probe = pd.DataFrame({"k": np.arange(20).astype(np.int64)})
    # strategy-specific table names: the compiled-program cache keys on the
    # plan, and a cache hit would silently reuse the other strategy's program
    c.create_table(f"bucket_build_{strategy}", big)
    c.create_table(f"bucket_probe_{strategy}", probe)
    fb = compiled.stats["fallbacks"]
    comp, eager = _both_paths(
        c, f"SELECT k FROM bucket_probe_{strategy} WHERE k IN "
           f"(SELECT k FROM bucket_build_{strategy})")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["fallbacks"] == fb
    c.drop_table(f"bucket_build_{strategy}")
    c.drop_table(f"bucket_probe_{strategy}")


@_needs_compiled
def test_unsupported_plan_falls_back(c):
    # LAG reads its offset constant on the host: outside the compiled subset
    uns = compiled.stats["unsupported"]
    r = c.sql("SELECT b, LAG(b, 1) OVER (ORDER BY b) AS lb FROM df_simple",
              return_futures=False)
    assert r["lb"].tolist()[1:] == [1.1, 2.2]
    assert compiled.stats["unsupported"] > uns


@_needs_compiled
def test_window_compiles(c):
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    r = c.sql("SELECT b, ROW_NUMBER() OVER (ORDER BY b DESC) AS rn, "
              "SUM(b) OVER (PARTITION BY a) AS sb FROM df_simple",
              return_futures=False)
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1
    assert sorted(r["rn"].tolist()) == [1, 2, 3]


def test_compiled_disabled_by_env(c, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE", "0")
    n = compiled.stats["compiles"] + compiled.stats["hits"]
    r = c.sql("SELECT SUM(a) AS s FROM df_simple", return_futures=False)
    assert r["s"][0] == 6
    assert compiled.stats["compiles"] + compiled.stats["hits"] == n


def test_nan_join_key_matches_nothing(c):
    """NaN join keys must not match 0.0 (or other NaNs) on the compiled path
    (the hash canonicalizes NaN but match verification must not)."""
    import pandas as pd
    c.create_table("nan_l", pd.DataFrame({"x": [0.0, 1.0], "y": [0.0, 1.0]}))
    c.create_table("nan_r", pd.DataFrame({"f": [0.0, 1.0], "tag": [10, 20]}))
    comp, eager = _both_paths(
        c, "SELECT t.f2, r.tag FROM (SELECT x / y AS f2 FROM nan_l) t "
           "JOIN nan_r r ON t.f2 = r.f")
    _assert_same(comp, eager, ordered=False)
    assert len(comp) == 1  # only the 1.0 row; 0/0 -> NaN matches nothing


def test_desc_sort_nan_last_both_paths(c):
    """ORDER BY ... DESC keeps NaN last (XLA semantics) on both executors."""
    import pandas as pd
    c.create_table("nan_s", pd.DataFrame({"x": [0.0, 2.0, 1.0],
                                          "y": [0.0, 1.0, 1.0]}))
    comp, eager = _both_paths(
        c, "SELECT x / y AS r FROM nan_s ORDER BY r DESC")
    import numpy as np
    assert np.isnan(comp["r"].iloc[-1]) and np.isnan(eager["r"].iloc[-1])
    _assert_same(comp, eager, ordered=True)


@_needs_compiled
def test_distinct_aggregate_compiles(c, user_table_1):
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    comp, eager = _both_paths(
        c, "SELECT user_id, COUNT(DISTINCT b) AS n, SUM(DISTINCT b) AS s "
           "FROM user_table_1 GROUP BY user_id")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1
    comp, eager = _both_paths(
        c, "SELECT COUNT(DISTINCT b) AS n FROM user_table_1")
    _assert_same(comp, eager, ordered=True)


@_needs_compiled
def test_scalar_subquery_compiles(c, user_table_1):
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    comp, eager = _both_paths(
        c, "SELECT user_id, b FROM user_table_1 "
           "WHERE b > (SELECT AVG(b) FROM user_table_1)")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1


@_needs_compiled
def test_left_join_residual_compiles(c, user_table_1, user_table_2):
    # LEFT JOIN with a non-equi ON conjunct: the residual must knock out
    # pairs (NULL build side) without dropping probe rows
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    # the cross-side conjunct survives push_join_side_conditions (a
    # build-only one would be rewritten into a pre-join filter and never
    # reach the compiled residual path)
    comp, eager = _both_paths(
        c, "SELECT u2.user_id, u2.c, u1.b FROM user_table_2 u2 "
           "LEFT JOIN user_table_1 u1 "
           "ON u2.user_id = u1.user_id AND u1.b > u2.user_id")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1


@_needs_compiled
def test_anti_join_comparison_residual_compiles(c, monkeypatch):
    # NOT EXISTS with a build-vs-probe comparison residual (TPC-H Q21's
    # l3.l_suppkey <> l1.l_suppkey): per-hash-run build min/max/count decide
    # existence in-program on the merge path
    from dask_sql_tpu.ops import pallas_kernels
    monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
    orders_df = pd.DataFrame({"ok": [1, 1, 1, 2, 2, 3],
                              "sk": [10, 11, 10, 20, 20, 30]})
    c.create_table("resid_li", orders_df)
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    comp, eager = _both_paths(
        c, "SELECT l1.ok, l1.sk FROM resid_li l1 WHERE NOT EXISTS ("
           "SELECT * FROM resid_li l2 WHERE l2.ok = l1.ok AND l2.sk <> l1.sk)")
    _assert_same(comp, eager, ordered=False)
    # order 1 has two distinct suppliers -> excluded; orders 2,3 survive
    assert sorted(comp.ok.unique().tolist()) == [2, 3]
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1
    c.drop_table("resid_li")


@_needs_compiled
def test_cache_hit_on_reloaded_identical_data(c):
    """Reloading the same data (new Table objects, equal content) must HIT
    the program cache: the key is shapes/dtypes + dictionary content, not
    table identity — the reference recompiles nothing on new partitions
    either, and a per-load recompile would dwarf query time in any
    load-query-drop loop."""
    from dask_sql_tpu import Context

    def make_df():
        return pd.DataFrame({"k": ["x", "y", "x", "z"] * 5,
                             "v": list(range(20))})

    c1 = Context()
    c1.create_table("reload_t", make_df())
    q = "SELECT k, SUM(v) AS s FROM reload_t GROUP BY k"
    r1 = c1.sql(q, return_futures=False)
    compiles = compiled.stats["compiles"]
    hits = compiled.stats["hits"]

    c2 = Context()  # fresh context, freshly-built identical frame
    c2.create_table("reload_t", make_df())
    r2 = c2.sql(q, return_futures=False)
    assert compiled.stats["compiles"] == compiles, "recompiled on reload"
    assert compiled.stats["hits"] == hits + 1
    pd.testing.assert_frame_equal(
        r1.sort_values("k", ignore_index=True),
        r2.sort_values("k", ignore_index=True), check_dtype=False)

    # different dictionary content => different program (string constants
    # are baked in), so this must NOT hit the stale entry
    c3 = Context()
    df3 = make_df()
    df3.loc[3, "k"] = "w"  # same shape, same dtypes, new dictionary
    c3.create_table("reload_t", df3)
    r3 = c3.sql(q, return_futures=False)
    assert compiled.stats["compiles"] == compiles + 1
    assert set(r3["k"]) == {"w", "x", "y", "z"}
    assert int(r3.set_index("k").loc["w", "s"]) == 3


@_needs_compiled
def test_wide_build_side_merge_join(c, monkeypatch):
    """Wide build sides ride the sorted-probe join directly: its channel
    count is constant (columns arrive by row-id gathers), so the r1/r2
    width-triggered strategy switch no longer exists and width must not
    change results or the single-program property."""
    from dask_sql_tpu.ops import pallas_kernels
    monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
    monkeypatch.delenv("DSQL_STRATEGY", raising=False)
    wide = pd.DataFrame({"user_id": [1, 2, 3],
                         **{f"w{i}": [i, i + 1, i + 2] for i in range(6)}})
    c.create_table("wide_build", wide)
    before = compiled.stats["compiles"] + compiled.stats["hits"]
    comp, eager = _both_paths(
        c, "SELECT u1.user_id, w.w0, w.w5 FROM user_table_1 u1 "
           "JOIN wide_build w ON u1.user_id = w.user_id")
    _assert_same(comp, eager, ordered=False)
    assert compiled.stats["compiles"] + compiled.stats["hits"] == before + 1
    c.drop_table("wide_build")


@_needs_compiled
def test_runtime_verdict_not_inherited_by_reloaded_data(c):
    """A duplicate-build-key fallback is pinned to the exact tables (uid),
    NOT the layout fingerprint: reloading corrected data with the same
    shapes/dtypes must get the compiled path back."""
    from dask_sql_tpu import Context

    q = ("SELECT p.k, b.v FROM rv_probe p JOIN rv_build b ON p.k = b.k")
    c1 = Context()
    c1.create_table("rv_probe", pd.DataFrame({"k": [1, 2, 3, 4]}))
    c1.create_table("rv_build", pd.DataFrame({"k": [1, 1, 2, 4],
                                              "v": [9, 8, 7, 6]}))
    fb = compiled.stats["fallbacks"]
    c1.sql(q, return_futures=False)
    assert compiled.stats["fallbacks"] > fb  # non-unique build -> eager

    c2 = Context()  # same layout, corrected (unique) keys
    c2.create_table("rv_probe", pd.DataFrame({"k": [1, 2, 3, 4]}))
    c2.create_table("rv_build", pd.DataFrame({"k": [1, 3, 2, 4],
                                              "v": [9, 8, 7, 6]}))
    fb2 = compiled.stats["fallbacks"]
    r = c2.sql(q, return_futures=False)
    assert compiled.stats["fallbacks"] == fb2, "inherited stale exile"
    assert sorted(r["k"].tolist()) == [1, 2, 3, 4]


@_needs_compiled
def test_compiled_path_uses_device_string_bitmap(monkeypatch):
    """Above the dictionary-cardinality threshold the COMPILED path picks
    the device bytes-matrix LIKE bitmap (r2 left it eager-only): the bitmap
    computes eagerly at trace time and bakes into the program as a
    constant, keyed by dictionary content."""
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.ops import strings_fast
    from dask_sql_tpu.physical import compiled

    monkeypatch.setattr(strings_fast, "DEVICE_STRING_THRESHOLD", 1)
    c = Context()
    c.create_table("t", pd.DataFrame(
        {"s": ["special requests", "plain", "very special requests here",
               "nothing"] * 50}))
    before_dev = strings_fast.stats["device_bitmaps"]
    before = dict(compiled.stats)
    out = c.sql("SELECT COUNT(*) AS n FROM t WHERE s LIKE "
                "'%special%requests%'", return_futures=False)
    assert out["n"].tolist() == [100]
    assert compiled.stats["compiles"] > before["compiles"]  # compiled ran
    assert strings_fast.stats["device_bitmaps"] > before_dev  # device path


@pytest.mark.parametrize("workers", ["1", "4"])
def test_plan_splitting_matches_whole(monkeypatch, workers):
    """Plans above the heavy-node budget execute as a stage graph of
    bounded compiled programs with materialized temps between them (XLA:TPU
    compile time grows superlinearly with fused join count; TPC-H Q2's
    9-heavy program never finished compiling over the tunnel).  Forced low
    budget via the legacy DSQL_SPLIT_HEAVY knob (compat path): the staged
    answer must agree with the eager answer and leave no temp schema
    behind — in both the serial and the worker-pool executor."""
    import pandas as pd

    from benchmarks.tpch import QUERIES, generate_tpch
    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled as cm

    monkeypatch.setenv("DSQL_SPLIT_HEAVY", "3")
    monkeypatch.setenv("DSQL_COMPILE_WORKERS", workers)
    monkeypatch.delenv("DSQL_STRATEGY", raising=False)
    data = generate_tpch(0.005)
    c1 = Context()
    for n, f in data.items():
        c1.create_table(n, f)
    graphs = cm.stats["stage_graphs"]
    for q in (2, 21, 18):
        got = c1.sql(QUERIES[q], return_futures=False)
        monkeypatch.setenv("DSQL_COMPILE", "0")
        want = c1.sql(QUERIES[q], return_futures=False)
        monkeypatch.setenv("DSQL_COMPILE", "1")
        pd.testing.assert_frame_equal(
            got.reset_index(drop=True), want.reset_index(drop=True),
            check_dtype=False, rtol=1e-5, atol=1e-8)
        split_schema = c1.schema.get("__split__")
        assert not (split_schema and split_schema.tables), \
            "split temps must be cleaned up"
    assert cm.stats["stage_graphs"] > graphs, "no plan was staged"


def test_learned_split_hint(monkeypatch, tmp_path):
    """A persisted "__split__" caps hint makes the plan execute as a stage
    graph (same answer), without any env knob — the mechanism that stops a
    plan whose whole program crashes the remote TPU compiler from
    re-crashing it in every process."""
    import pandas as pd

    from benchmarks.tpch import QUERIES, generate_tpch
    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled as cm

    monkeypatch.setenv("DSQL_CAPS_FILE", str(tmp_path / "caps.json"))
    monkeypatch.setattr(cm, "_caps_disk", None)
    monkeypatch.setattr(cm, "_learned_caps", type(cm._learned_caps)())
    data = generate_tpch(0.005)
    c = Context()
    for n, f in data.items():
        c.create_table(n, f)

    staged = []  # stage counts of each graph execution
    orig = cm._execute_stage_graph

    def spy(graph, context, query_fp, split_limit):
        staged.append(len(graph.stages))
        return orig(graph, context, query_fp, split_limit)

    monkeypatch.setattr(cm, "_execute_stage_graph", spy)

    # no hint: Q3 (3 heavy nodes, default budget 6) runs as one program
    got1 = c.sql(QUERIES[3], return_futures=False)
    assert staged == []

    # write the hint for this exact plan shape, as the failure path would
    # (which fingerprints the PARAMETERIZED plan — literals hoisted)
    from dask_sql_tpu.sql.parser import parse_sql
    plan = cm._maybe_parameterize(
        c._get_plan(parse_sql(QUERIES[3])[0].query), count=False)
    from dask_sql_tpu.ops.pallas_kernels import _strategy_on_tpu
    scans = []
    key = (cm._fp_plan(plan, c, scans), cm._fp_inputs(scans),
           bool(_strategy_on_tpu()), cm._mesh_signature(c))
    cm._learned_caps_put(key, {"__split__": 1})

    got2 = c.sql(QUERIES[3], return_futures=False)
    assert staged and staged[0] >= 2, "hint must force the staged path"
    pd.testing.assert_frame_equal(got1.reset_index(drop=True),
                                  got2.reset_index(drop=True),
                                  check_dtype=False, rtol=1e-5, atol=1e-8)

    # a FRESH process state (cleared memo) still reads the hint from disk
    monkeypatch.setattr(cm, "_caps_disk", None)
    monkeypatch.setattr(cm, "_learned_caps", type(cm._learned_caps)())
    staged.clear()
    c.sql(QUERIES[3], return_futures=False)
    assert staged and staged[0] >= 2


@_needs_compiled
def test_cross_query_stage_cache_hit(monkeypatch):
    """Two queries sharing a subplan must share the shared stage's compiled
    program: the second query's stage comes back as a cache hit from a
    DIFFERENT origin query — observable as stats["cross_query_hits"]."""
    import numpy as np

    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled as cm

    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    rng = np.random.RandomState(0)
    c = Context()
    c.create_table("xq_fact", pd.DataFrame(
        {"k": rng.randint(0, 50, 1000), "v": rng.rand(1000)}))
    c.create_table("xq_dim", pd.DataFrame(
        {"k": np.arange(50), "w": np.arange(50) * 0.5}))
    shared = "(SELECT k, SUM(v) AS s FROM xq_fact GROUP BY k) x"
    before = dict(cm.stats)
    c.sql(f"SELECT x.k, x.s, d.w FROM {shared} "
          "JOIN xq_dim d ON x.k = d.k", return_futures=False)
    assert cm.stats["stage_graphs"] > before["stage_graphs"]
    assert cm.stats["cross_query_hits"] == before["cross_query_hits"]
    c.sql(f"SELECT x.k, x.s * 2 AS s2, d.w FROM {shared} "
          "JOIN xq_dim d ON x.k = d.k WHERE d.w > 5", return_futures=False)
    assert cm.stats["cross_query_hits"] > before["cross_query_hits"], \
        "shared subplan stage did not hit across queries"


def test_stage_temps_cleaned_on_exception(monkeypatch):
    """__split__ temp tables must be unregistered even when a stage raises
    mid-graph (the exception path of _execute_stage_graph's cleanup)."""
    import numpy as np

    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled as cm
    from dask_sql_tpu.sql.parser import parse_sql

    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    monkeypatch.setenv("DSQL_COMPILE_WORKERS", "1")  # deterministic order
    rng = np.random.RandomState(0)
    c = Context()
    c.create_table("exc_fact", pd.DataFrame(
        {"k": rng.randint(0, 20, 500), "v": rng.rand(500)}))
    c.create_table("exc_dim", pd.DataFrame(
        {"k": np.arange(20), "w": np.arange(20) * 1.5}))
    plan = c._get_plan(parse_sql(
        "SELECT x.k, x.s, d.w FROM (SELECT k, SUM(v) AS s FROM exc_fact "
        "GROUP BY k) x JOIN exc_dim d ON x.k = d.k")[0].query)

    graphs = []
    orig_part = cm._partition_plan

    def part_spy(p, budget, context):
        g = orig_part(p, budget, context)
        graphs.append(g)
        return g

    orig_single = cm._execute_single

    def boom(p, context, query_fp, split_limit=None, in_stage=False):
        if graphs and p is graphs[-1].stages[-1].plan:
            raise RuntimeError("injected root-stage failure")
        return orig_single(p, context, query_fp, split_limit,
                           in_stage=in_stage)

    monkeypatch.setattr(cm, "_partition_plan", part_spy)
    monkeypatch.setattr(cm, "_execute_single", boom)
    with pytest.raises(RuntimeError, match="injected"):
        cm.try_execute_compiled(plan, c)
    assert graphs, "plan was not staged"
    split_schema = c.schema.get("__split__")
    assert not (split_schema and split_schema.tables), \
        "exception path leaked __split__ temps"


def test_filter_compaction_learned_caps(monkeypatch):
    """Learned-capacity compaction after selective filters (TPU strategy):
    the compiled result must match eager, engage only above the size
    threshold, learn a tight cap via one shrink recompile, and not flip
    join build sides onto duplicate-key fact streams (the weight
    mechanism)."""
    import numpy as np

    from dask_sql_tpu.physical import compiled as cm

    monkeypatch.setenv("DSQL_STRATEGY", "tpu")
    monkeypatch.delenv("DSQL_CAPS_FILE", raising=False)
    rng = np.random.RandomState(0)
    n = 1 << 17  # above the compaction threshold
    fact = pd.DataFrame({
        "k": rng.randint(0, 5000, n),
        "sel": rng.randint(0, 100, n),
        "v": rng.randn(n),
    })
    dim = pd.DataFrame({"k": np.arange(5000),
                        "name": [f"d{i}" for i in range(5000)]})
    from dask_sql_tpu import Context
    ctx = Context()
    ctx.create_table("fact", fact)
    ctx.create_table("dim", dim)
    q = ("SELECT name, SUM(v) AS s, COUNT(*) AS c FROM fact "
         "JOIN dim ON fact.k = dim.k WHERE sel < 3 GROUP BY name")
    rec = cm.stats["recompiles"]
    fb = cm.stats["fallbacks"]
    got = ctx.sql(q, return_futures=False)
    monkeypatch.setenv("DSQL_COMPILE", "0")
    want = ctx.sql(q, return_futures=False)
    monkeypatch.setenv("DSQL_COMPILE", "1")
    cols = list(got.columns)
    pd.testing.assert_frame_equal(
        got.sort_values(cols, ignore_index=True),
        want.sort_values(cols, ignore_index=True),
        check_dtype=False, rtol=1e-6, atol=1e-9)
    assert cm.stats["fallbacks"] == fb, "compaction must not cause fallback"
    assert cm.stats["recompiles"] > rec, "shrink recompile expected"
