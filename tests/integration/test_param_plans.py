"""Integration: parameterized plan identity + PREPARE/EXECUTE (ISSUE 16).

Covers the acceptance surface:
- repeated arrivals of one query shape with different literals compile
  ONCE and then hit the in-memory program cache;
- the result cache stays literal-isolated: distinct literal sets never
  share a cached answer, while repeats of the same literals still hit;
- PREPARE / EXECUTE / DEALLOCATE end to end, the per-context registry
  surfaced as system.prepared, and the ``params=`` client API;
- a FRESH interpreter (and its in-process simulation) serves a
  never-seen literal of a previously-seen shape from the persistent
  program store with zero XLA compiles;
- DSQL_PARAM_PLANS=0 restores value-baked program identity.
"""
import json
import os
import subprocess
import sys

import pandas as pd
import pytest

import jax

from dask_sql_tpu import Context
from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import program_store as ps
from dask_sql_tpu.runtime import result_cache as rc
from dask_sql_tpu.runtime import telemetry as tel


def _deltas(c0):
    now = tel.REGISTRY.counters()
    return {k: v - c0.get(k, 0) for k, v in now.items() if v != c0.get(k, 0)}


def _forget_programs():
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()
    with compiled._tier_lock:
        compiled._tier_done.clear()
        compiled._tier_inflight.clear()
    jax.clear_caches()


@pytest.fixture(autouse=True)
def _quiet(monkeypatch):
    monkeypatch.setenv("DSQL_TIERED", "0")
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "0")
    monkeypatch.delenv("DSQL_FAULT_INJECT", raising=False)


@pytest.fixture()
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": range(200), "b": [float(i) * 0.5 for i in range(200)]}))
    return c


def _oracle(df, lit):
    return df[(df.a > lit)][["a", "b"]].reset_index(drop=True)


# ---------------------------------------------------------------------------
# one compile per shape
# ---------------------------------------------------------------------------

def test_one_compile_many_literals(ctx):
    df = ctx.sql("SELECT a, b FROM t", return_futures=False)
    c0 = tel.REGISTRY.counters()
    for lit in (3, 17, 42, 99, 150):
        got = ctx.sql(f"SELECT a, b FROM t WHERE a > {lit}",
                      return_futures=False)
        pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                      _oracle(df, lit), check_dtype=False)
    d = _deltas(c0)
    assert d.get("compiles", 0) == 1, d
    assert d.get("param_plan_hits", 0) >= 4, d
    assert d.get("param_plans", 0) >= 5, d


def test_kill_switch_restores_value_baked_identity(ctx, monkeypatch):
    monkeypatch.setenv("DSQL_PARAM_PLANS", "0")
    c0 = tel.REGISTRY.counters()
    for lit in (3, 17, 42):
        ctx.sql(f"SELECT a, b FROM t WHERE a > {lit}")
    d = _deltas(c0)
    assert d.get("compiles", 0) == 3, d
    assert d.get("param_plans", 0) == 0, d
    assert d.get("param_plan_hits", 0) == 0, d


# ---------------------------------------------------------------------------
# result-cache isolation
# ---------------------------------------------------------------------------

def test_result_cache_never_shares_across_literals(ctx, monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "64")
    rc.get_cache().clear()
    try:
        r10 = ctx.sql("SELECT a, b FROM t WHERE a > 10",
                      return_futures=False)
        r50 = ctx.sql("SELECT a, b FROM t WHERE a > 50",
                      return_futures=False)
        assert len(r10) != len(r50)  # distinct literals, distinct answers
        c0 = tel.REGISTRY.counters()
        r10b = ctx.sql("SELECT a, b FROM t WHERE a > 10",
                       return_futures=False)
        d = _deltas(c0)
        assert d.get("result_cache_hits", 0) == 1, d  # same literal hits
        pd.testing.assert_frame_equal(r10, r10b)
        c1 = tel.REGISTRY.counters()
        r99 = ctx.sql("SELECT a, b FROM t WHERE a > 99",
                      return_futures=False)
        d2 = _deltas(c1)
        assert d2.get("result_cache_hits", 0) == 0, d2  # new literal misses
        pd.testing.assert_frame_equal(
            r99, _oracle(ctx.sql("SELECT a, b FROM t",
                                 return_futures=False), 99),
            check_dtype=False)
    finally:
        rc.get_cache().clear()


# ---------------------------------------------------------------------------
# PREPARE / EXECUTE / params=
# ---------------------------------------------------------------------------

def test_prepare_execute_roundtrip(ctx):
    df = ctx.sql("SELECT a, b FROM t", return_futures=False)
    ctx.sql("PREPARE above AS SELECT a, b FROM t WHERE a > ?")
    c0 = tel.REGISTRY.counters()
    for lit in (5, 25, 125):
        got = ctx.sql(f"EXECUTE above ({lit})", return_futures=False)
        pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                      _oracle(df, lit), check_dtype=False)
    d = _deltas(c0)
    assert d.get("prepared_executes", 0) == 3, d
    assert d.get("compiles", 0) <= 1, d

    sysp = ctx.sql("SELECT * FROM system.prepared", return_futures=False)
    assert list(sysp["name"]) == ["above"]
    assert int(sysp["num_params"][0]) == 1

    ctx.sql("DEALLOCATE above")
    with pytest.raises(RuntimeError, match="does not exist"):
        ctx.sql("EXECUTE above (1)")
    sysp = ctx.sql("SELECT * FROM system.prepared", return_futures=False)
    assert len(sysp) == 0


def test_execute_arity_checked(ctx):
    ctx.sql("PREPARE two AS SELECT a FROM t WHERE a > $1 AND b < $2")
    with pytest.raises(RuntimeError, match="requires 2 parameters"):
        ctx.sql("EXECUTE two (1)")
    got = ctx.sql("EXECUTE two (1, 5.0)", return_futures=False)
    assert len(got) > 0


def test_params_api_shares_program_with_inline_literals(ctx):
    df = ctx.sql("SELECT a, b FROM t", return_futures=False)
    _forget_programs()  # isolate from shapes other tests already compiled
    c0 = tel.REGISTRY.counters()
    inline = ctx.sql("SELECT a, b FROM t WHERE a > 30",
                     return_futures=False)
    marked = ctx.sql("SELECT a, b FROM t WHERE a > ?", params=[60],
                     return_futures=False)
    dollar = ctx.sql("SELECT a, b FROM t WHERE a > $1", params=[90],
                     return_futures=False)
    d = _deltas(c0)
    assert d.get("compiles", 0) == 1, d  # one shape, three spellings
    for lit, got in ((30, inline), (60, marked), (90, dollar)):
        pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                      _oracle(df, lit), check_dtype=False)


def test_unbound_marker_is_a_clear_error(ctx):
    from dask_sql_tpu.utils import ValidationException
    with pytest.raises(ValidationException,
                       match="[Pp]ositional parameter"):
        ctx.sql("SELECT a FROM t WHERE a > ?")


# ---------------------------------------------------------------------------
# cross-process program store: same shape, NEVER-SEEN literal
# ---------------------------------------------------------------------------

def test_store_serves_fresh_process_with_new_literal(ctx, tmp_path,
                                                     monkeypatch):
    monkeypatch.setenv("DSQL_PROGRAM_STORE", str(tmp_path / "programs"))
    _forget_programs()
    try:
        c0 = tel.REGISTRY.counters()
        cold = ctx.sql("SELECT a, b FROM t WHERE a > 10",
                       return_futures=False)
        d1 = _deltas(c0)
        assert d1.get("compiles", 0) == 1
        assert d1.get("program_store_stores", 0) >= 1

        _forget_programs()  # what a fresh process starts from
        c1 = tel.REGISTRY.counters()
        warm = ctx.sql("SELECT a, b FROM t WHERE a > 120",  # new literal
                       return_futures=False)
        d2 = _deltas(c1)
        assert d2.get("compiles", 0) == 0, d2
        assert d2.get("program_store_hits", 0) >= 1, d2
        assert d2.get("param_plan_hits", 0) >= 1, d2
        df = ctx.sql("SELECT a, b FROM t", return_futures=False)
        pd.testing.assert_frame_equal(warm.reset_index(drop=True),
                                      _oracle(df, 120), check_dtype=False)
        assert len(cold) != len(warm)
    finally:
        _forget_programs()


_CHILD = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
os.environ["DSQL_TIERED"] = "0"
import pandas as pd
from dask_sql_tpu import Context
from dask_sql_tpu.runtime import telemetry as tel

lit = sys.argv[2]
data = pd.read_feather(sys.argv[1])
c = Context()
c.create_table("t", data)
out = c.sql(f"SELECT a, b FROM t WHERE a > {lit}", return_futures=False)
snap = tel.REGISTRY.counters()
print(json.dumps({
    "rows": len(out),
    "compiles": snap["compiles"],
    "program_store_hits": snap["program_store_hits"],
    "program_store_stores": snap["program_store_stores"],
    "param_plan_hits": snap["param_plan_hits"],
}))
"""


@pytest.mark.slow  # two real interpreter launches; the in-process variant
# above proves the same seam on the tier-1 box, and scripts/param_smoke.py
# gates the cross-process version in CI
def test_fresh_interpreter_new_literal_zero_compiles(tmp_path):
    data_path = str(tmp_path / "t.feather")
    pd.DataFrame({"a": range(200),
                  "b": [float(i) * 0.5 for i in range(200)]}
                 ).to_feather(data_path)
    env = dict(os.environ,
               DSQL_PROGRAM_STORE=str(tmp_path / "programs"),
               JAX_PLATFORMS="cpu")
    env.pop("DSQL_FAULT_INJECT", None)

    outs = []
    for lit in ("10", "120"):  # DIFFERENT literal in the second process
        r = subprocess.run([sys.executable, "-c", _CHILD, data_path, lit],
                           capture_output=True, text=True, env=env,
                           timeout=240)
        assert r.returncode == 0, r.stderr[-2000:]
        outs.append(json.loads(r.stdout.strip().splitlines()[-1]))
    first, second = outs
    assert first["compiles"] >= 1
    assert first["program_store_stores"] >= 1
    assert second["compiles"] == 0, second
    assert second["program_store_hits"] >= 1, second
    assert second["param_plan_hits"] >= 1, second
    assert second["rows"] != first["rows"]
