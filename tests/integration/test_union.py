"""UNION / INTERSECT / EXCEPT tests (reference: tests/integration/test_union.py)."""
import pandas as pd

from tests.conftest import assert_eq


def test_union_all(c, df_simple):
    result = c.sql("SELECT a FROM df_simple UNION ALL SELECT a FROM df_simple")
    expected = pd.concat([df_simple[["a"]], df_simple[["a"]]])
    assert_eq(result, expected, check_row_order=False)


def test_union_distinct(c, df_simple):
    result = c.sql("SELECT a FROM df_simple UNION SELECT a FROM df_simple")
    expected = df_simple[["a"]].drop_duplicates()
    assert_eq(result, expected, check_row_order=False)


def test_union_mixed_types(c, df_simple):
    result = c.sql("SELECT a FROM df_simple UNION ALL SELECT b FROM df_simple")
    expected = pd.DataFrame({"a": list(df_simple["a"].astype(float)) + list(df_simple["b"])})
    assert_eq(result, expected, check_row_order=False)


def test_union_strings(c, string_table):
    result = c.sql(
        "SELECT a FROM string_table UNION ALL SELECT UPPER(a) AS a FROM string_table")
    expected = pd.DataFrame({"a": list(string_table["a"]) +
                             [s.upper() for s in string_table["a"]]})
    assert_eq(result, expected, check_row_order=False)


def test_intersect(c):
    c.create_table("i1", pd.DataFrame({"a": [1, 2, 3, 3]}))
    c.create_table("i2", pd.DataFrame({"a": [2, 3, 4]}))
    result = c.sql("SELECT a FROM i1 INTERSECT SELECT a FROM i2")
    assert_eq(result, pd.DataFrame({"a": [2, 3]}), check_row_order=False)


def test_except(c):
    c.create_table("e1", pd.DataFrame({"a": [1, 2, 3, 3]}))
    c.create_table("e2", pd.DataFrame({"a": [2, 4]}))
    result = c.sql("SELECT a FROM e1 EXCEPT SELECT a FROM e2")
    assert_eq(result, pd.DataFrame({"a": [1, 3]}), check_row_order=False)


def test_union_with_order_limit(c, df_simple):
    result = c.sql(
        """SELECT a FROM df_simple UNION ALL SELECT a FROM df_simple
           ORDER BY a DESC LIMIT 3""")
    expected = pd.DataFrame({"a": [3, 3, 2]})
    assert_eq(result, expected)
