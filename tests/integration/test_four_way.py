"""Four-way internal differential oracle.

The reference double-oracles against SQLite and PostgreSQL
(/root/reference/tests/integration/fixtures.py:188-288,
test_postgres.py:9-44).  Postgres/duckdb don't exist in this image, so the
engine's own redundancy substitutes: every query in a randomized corpus
executes through FOUR independent paths —

  1. eager     (per-op dispatch, physical/rel/executor.py)
  2. compiled  (whole-plan jit, physical/compiled.py — CPU strategies)
  3. mesh      (same compiled machinery but traced over row-sharded inputs
                with the TPU strategy set, executing as GSPMD programs)
  4. streaming (out-of-HBM chunked execution, physical/streaming.py)

and all pairs must agree; SQLite joins as a fifth, genuinely independent
voice where the dialect overlaps.  A bug must now be replicated across
sort-based AND hash-based kernels, padded AND sharded AND batched inputs,
to slip through — single-path bugs cannot.
"""
import os

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.parallel.mesh import default_mesh
from dask_sql_tpu.physical.streaming import StreamingUnsupported
from tests.conftest import make_rand_df

# ---------------------------------------------------------------------------
# corpus: (name, sql, sqlite_ok) over tables a (fact, 400 rows) and
# d (dimension, 40 rows).  Shapes chosen to cross joins, group-bys,
# DISTINCT, CASE, HAVING, strings, NULL keys, and sort/limit.
# ---------------------------------------------------------------------------
CORPUS = [
    ("proj", "SELECT k, v*2 AS w, s FROM a", True),
    ("filter", "SELECT k, v FROM a WHERE v > 3 AND k < 7", True),
    ("filter_null", "SELECT k, f FROM a WHERE f IS NULL OR f > 5", True),
    ("agg_global",
     "SELECT COUNT(*) AS n, SUM(v) AS sv, AVG(v) AS av, MIN(v) AS mi, "
     "MAX(v) AS ma FROM a", True),
    ("agg_group",
     "SELECT k, COUNT(*) AS n, SUM(v) AS sv, AVG(f) AS af FROM a "
     "GROUP BY k", True),
    ("agg_string_key",
     "SELECT s, COUNT(*) AS n, SUM(v) AS sv FROM a GROUP BY s", True),
    ("agg_multi_key",
     "SELECT k, s, COUNT(*) AS n FROM a GROUP BY k, s", True),
    ("agg_null_key",
     "SELECT g, COUNT(*) AS n, SUM(v) AS sv FROM a GROUP BY g", True),
    ("agg_having",
     "SELECT k, SUM(v) AS sv FROM a GROUP BY k HAVING SUM(v) > 20", True),
    ("agg_case",
     "SELECT k, SUM(CASE WHEN v > 5 THEN v ELSE 0 END) AS sv FROM a "
     "GROUP BY k", True),
    ("agg_distinct", "SELECT COUNT(DISTINCT k) AS n FROM a", True),
    ("agg_distinct_group",
     "SELECT s, COUNT(DISTINCT k) AS n FROM a GROUP BY s", True),
    ("distinct_rows", "SELECT DISTINCT k, s FROM a", True),
    ("join_inner",
     "SELECT a.k, a.v, d.w FROM a JOIN d ON a.k = d.k WHERE d.w > 2",
     True),
    ("join_agg",
     "SELECT d.t, COUNT(*) AS n, SUM(a.v) AS sv FROM a "
     "JOIN d ON a.k = d.k GROUP BY d.t", True),
    ("join_left",
     "SELECT a.k, d.w FROM a LEFT JOIN d ON a.k = d.k", True),
    ("join_multi_key",
     "SELECT a.k, a.v FROM a JOIN d ON a.k = d.k AND a.s = d.s", True),
    ("semi",
     "SELECT k, v FROM a WHERE EXISTS "
     "(SELECT 1 FROM d WHERE d.k = a.k AND d.w > 3)", True),
    ("anti",
     "SELECT k, v FROM a WHERE NOT EXISTS "
     "(SELECT 1 FROM d WHERE d.k = a.k)", True),
    ("in_subquery",
     "SELECT k, v FROM a WHERE k IN (SELECT k FROM d WHERE w > 5)", True),
    ("not_in",
     "SELECT k, v FROM a WHERE k NOT IN (SELECT k FROM d WHERE w > 5)",
     True),
    ("not_in_empty",
     # x NOT IN (empty) is TRUE for every x, NULL included
     "SELECT f FROM a WHERE f NOT IN (SELECT w FROM d WHERE w > 999)",
     False),  # sqlite's read_sql NULL/float frame shape differs; engine-only
    ("scalar_subquery",
     "SELECT k, v FROM a WHERE v > (SELECT AVG(v) FROM a)", True),
    ("order_limit",
     "SELECT k, v FROM a ORDER BY v DESC, k ASC LIMIT 17", True),
    ("order_nulls",
     "SELECT f, k FROM a ORDER BY f, k LIMIT 23", False),  # NULL order differs
    ("union_all",
     "SELECT k, v FROM a WHERE v > 7 UNION ALL "
     "SELECT k, v FROM a WHERE v < 2", True),
    ("union_distinct",
     "SELECT k FROM a WHERE v > 5 UNION SELECT k FROM d", True),
    ("expr_zoo",
     "SELECT k, ABS(v - 5) AS av, CASE WHEN s LIKE 's1%' THEN 1 ELSE 0 END "
     "AS m, COALESCE(f, -1) AS cf FROM a", True),
    ("strings",
     "SELECT UPPER(s) AS u, SUBSTR(s, 1, 2) AS p, COUNT(*) AS n FROM a "
     "GROUP BY UPPER(s), SUBSTR(s, 1, 2)", True),
    ("between",
     "SELECT k, v FROM a WHERE v BETWEEN 2 AND 8 ORDER BY k, v LIMIT 50",
     True),
    ("agg_over_join_null",
     "SELECT d.t, SUM(a.f) AS sf FROM a JOIN d ON a.k = d.k GROUP BY d.t",
     True),
    ("nested",
     "SELECT t, n FROM (SELECT d.t AS t, COUNT(*) AS n FROM a "
     "JOIN d ON a.k = d.k GROUP BY d.t) x WHERE n > 5", True),
]


def _tables():
    a = make_rand_df(400, k=int, v=float, f=(float, 60), s=str, g=(str, 50))
    # widen k's range so join keys overlap partially with d
    rng = np.random.RandomState(7)
    a["k"] = rng.randint(0, 13, len(a)).astype("int64")
    d = pd.DataFrame({
        "k": np.arange(0, 20, 2),
        "w": np.round(rng.rand(10) * 10, 3),
        "s": rng.choice([f"s{i}" for i in range(6)], 10).astype(object),
        "t": rng.choice(["x", "y", "z"], 10).astype(object),
    })
    return a, d


def _canon(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy().reset_index(drop=True)
    for col in out.columns:
        s = out[col]
        if pd.api.types.is_float_dtype(s):
            out[col] = s.astype(np.float64).round(6)
        elif s.dtype == object:
            out[col] = s.where(pd.notna(s), None)
    return out.sort_values(list(out.columns),
                           ignore_index=True, na_position="last")


def _assert_same(tag_a, got, tag_b, want):
    ga, gb = _canon(got), _canon(want)
    assert list(ga.columns) == list(gb.columns), (tag_a, tag_b)
    pd.testing.assert_frame_equal(ga, gb, check_dtype=False,
                                  rtol=1e-5, atol=1e-6,
                                  obj=f"{tag_a} vs {tag_b}")


@pytest.fixture(scope="module")
def four_contexts():
    a, d = _tables()
    eager = Context()          # queried with DSQL_COMPILE=0
    comp = Context()
    mesh_ctx = None
    mesh = default_mesh()
    if mesh.devices.size >= 2:
        mesh_ctx = Context(mesh=mesh)
    stream = Context()
    for ctx in filter(None, (eager, comp, mesh_ctx)):
        ctx.create_table("a", a)
        ctx.create_table("d", d)
    stream.create_table("a", a, chunked=True, batch_rows=64)
    stream.create_table("d", d)
    return eager, comp, mesh_ctx, stream, a, d


@pytest.mark.parametrize("name,sql,sqlite_ok",
                         CORPUS, ids=[c[0] for c in CORPUS])
def test_four_way(four_contexts, name, sql, sqlite_ok, monkeypatch):
    eager_ctx, comp_ctx, mesh_ctx, stream_ctx, a, d = four_contexts

    monkeypatch.setenv("DSQL_COMPILE", "0")
    eager = eager_ctx.sql(sql, return_futures=False)
    monkeypatch.delenv("DSQL_COMPILE")

    comp = comp_ctx.sql(sql, return_futures=False)
    _assert_same("compiled", comp, "eager", eager)

    if mesh_ctx is not None:
        from dask_sql_tpu.ops import pallas_kernels
        # the mesh runs the TPU strategy set — what executes on real chips
        monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)
        mesh = mesh_ctx.sql(sql, return_futures=False)
        monkeypatch.undo()
        monkeypatch.delenv("DSQL_COMPILE", raising=False)
        _assert_same("mesh", mesh, "eager", eager)

    try:
        stream = stream_ctx.sql(sql, return_futures=False)
        _assert_same("streaming", stream, "eager", eager)
    except StreamingUnsupported:
        pass  # the streaming algebra rejects this shape loudly — fine

    if sqlite_ok:
        import sqlite3
        conn = sqlite3.connect(":memory:")
        a.to_sql("a", conn, index=False)
        d.to_sql("d", conn, index=False)
        try:
            expected = pd.read_sql(sql, conn)
        finally:
            conn.close()
        _assert_same("engine", eager, "sqlite", expected)
