"""GROUP BY tests (reference: tests/integration/test_groupby.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_group_by(c, user_table_1):
    result = c.sql(
        "SELECT user_id, SUM(b) AS S FROM user_table_1 GROUP BY user_id")
    expected = (user_table_1.groupby("user_id")["b"].sum()
                .reset_index().rename(columns={"b": "S"}))
    assert_eq(result, expected, check_row_order=False)


def test_group_by_all(c, df):
    result = c.sql("SELECT SUM(b) AS S, SUM(2*b) AS S2 FROM df")
    expected = pd.DataFrame({"S": [df["b"].sum()], "S2": [2 * df["b"].sum()]})
    assert_eq(result, expected)


def test_group_by_filtered(c, user_table_1):
    result = c.sql(
        """SELECT user_id,
                  SUM(b) FILTER (WHERE user_id = 2) AS "S1",
                  SUM(b) AS "S2"
           FROM user_table_1 GROUP BY user_id""")
    expected = pd.DataFrame({
        "user_id": [1, 2, 3],
        "S1": [np.nan, 4.0, np.nan],
        "S2": [3, 4, 3],
    })
    assert_eq(result, expected, check_row_order=False)


def test_group_by_case(c, user_table_1):
    result = c.sql(
        """SELECT user_id + 1 AS "u", SUM(CASE WHEN b = 3 THEN 1 ELSE 0 END) AS "S"
           FROM user_table_1 GROUP BY user_id + 1""")
    expected = pd.DataFrame({"u": [2, 3, 4], "S": [1, 1, 1]})
    assert_eq(result, expected, check_row_order=False)


def test_group_by_nan(c):
    frame = pd.DataFrame({"c": [3, float("nan"), 1], "d": [1, 2, 3]})
    c.create_table("nan_df", frame)
    result = c.sql("SELECT c, SUM(d) AS s FROM nan_df GROUP BY c").to_pandas()
    # NULL forms its own group (SQL GROUP BY semantics)
    assert len(result) == 3


def test_aggregations(c, user_table_1):
    result = c.sql(
        """SELECT user_id,
                  AVG(b) AS "a", SUM(b) AS "s", COUNT(b) AS "c",
                  MIN(b) AS "mi", MAX(b) AS "ma",
                  EVERY(b = 3) AS "e", BIT_AND(b) AS "ba", BIT_OR(b) AS "bo",
                  SINGLE_VALUE(user_id) AS "sv", ANY_VALUE(b) AS "av"
           FROM user_table_1 GROUP BY user_id""").to_pandas()
    g = user_table_1.groupby("user_id")["b"]
    expected = pd.DataFrame({
        "user_id": g.mean().index,
        "a": g.mean().values, "s": g.sum().values, "c": g.count().values,
        "mi": g.min().values, "ma": g.max().values,
        "e": g.apply(lambda s: bool((s == 3).all())).values,
        "ba": g.apply(lambda s: np.bitwise_and.reduce(s.values)).values,
        "bo": g.apply(lambda s: np.bitwise_or.reduce(s.values)).values,
        "sv": g.mean().index,
        "av": g.first().values,
    })
    assert_eq(result.sort_values("user_id").reset_index(drop=True),
              expected.reset_index(drop=True))


def test_stats_aggregation(c, user_table_1):
    result = c.sql(
        """SELECT user_id,
                  STDDEV(b) AS "std", VAR_SAMP(b) AS "vs",
                  STDDEV_POP(b) AS "sp", VAR_POP(b) AS "vp"
           FROM user_table_1 GROUP BY user_id""").to_pandas().sort_values("user_id")
    g = user_table_1.groupby("user_id")["b"]
    np.testing.assert_allclose(result["std"].values, g.std().values, rtol=1e-9, equal_nan=True)
    np.testing.assert_allclose(result["vs"].values, g.var().values, rtol=1e-9, equal_nan=True)
    np.testing.assert_allclose(result["sp"].values, g.std(ddof=0).values, rtol=1e-9)
    np.testing.assert_allclose(result["vp"].values, g.var(ddof=0).values, rtol=1e-9)


def test_group_by_distinct(c, user_table_1):
    result = c.sql(
        """SELECT user_id, COUNT(DISTINCT b) AS "cd", SUM(DISTINCT b) AS "sd"
           FROM user_table_1 GROUP BY user_id""")
    g = user_table_1.groupby("user_id")["b"]
    expected = pd.DataFrame({
        "user_id": g.nunique().index,
        "cd": g.nunique().values,
        "sd": g.apply(lambda s: s.drop_duplicates().sum()).values,
    })
    assert_eq(result, expected, check_row_order=False)


def test_count_star(c, long_table):
    result = c.sql("SELECT a, COUNT(*) AS n FROM long_table GROUP BY a")
    expected = long_table.groupby("a").size().reset_index(name="n")
    assert_eq(result, expected, check_row_order=False)


def test_count_star_no_group(c, long_table, user_table_1):
    # whole-table COUNT(*) references no input columns at all; the plan must
    # still carry the row count through the pruned pre-projection
    result = c.sql("SELECT COUNT(*) AS n FROM long_table")
    assert_eq(result, pd.DataFrame({"n": [len(long_table)]}))
    result = c.sql("SELECT COUNT(*) AS n FROM user_table_1 WHERE user_id = 2")
    assert_eq(result, pd.DataFrame({"n": [int((user_table_1.user_id == 2).sum())]}))
    result = c.sql(
        "SELECT COUNT(*) AS n FROM user_table_1 t1, user_table_1 t2 "
        "WHERE t1.user_id = t2.b")
    merged = user_table_1.merge(user_table_1, left_on="user_id", right_on="b")
    assert_eq(result, pd.DataFrame({"n": [len(merged)]}))


def test_having(c, user_table_1):
    result = c.sql(
        "SELECT user_id, SUM(b) AS s FROM user_table_1 GROUP BY user_id HAVING SUM(b) > 3")
    expected = pd.DataFrame({"user_id": [2], "s": [4]})
    assert_eq(result, expected)


def test_group_by_null(c, user_table_nan):
    result = c.sql(
        "SELECT c, COUNT(*) AS n FROM user_table_nan GROUP BY c").to_pandas()
    assert len(result) == 3


def test_groupby_ordinal_and_alias(c, user_table_1):
    r1 = c.sql("SELECT user_id AS u, SUM(b) AS s FROM user_table_1 GROUP BY 1")
    r2 = c.sql("SELECT user_id AS u, SUM(b) AS s FROM user_table_1 GROUP BY u")
    expected = (user_table_1.groupby("user_id")["b"].sum().reset_index()
                .rename(columns={"user_id": "u", "b": "s"}))
    assert_eq(r1, expected, check_row_order=False)
    assert_eq(r2, expected, check_row_order=False)
