"""End-to-end watchtower correlation (ISSUE 15 acceptance): one trace ID
follows a query across the server wire, the span tree, the
flight-recorder envelope, and system.events — including a query run in a
CHILD process against shared history/events files — plus the
/v1/events long-poll endpoint and trace headers on the error paths."""
import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_EVENTS", "1")
    monkeypatch.setenv("DSQL_EVENTS_FILE", str(tmp_path / "events.jsonl"))
    monkeypatch.setenv("DSQL_HISTORY_FILE", str(tmp_path / "hist.jsonl"))
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.runtime import events as ev
    from dask_sql_tpu.server.app import run_server

    ev._reset_for_tests()
    context = Context()
    context.create_table("t", {"a": np.arange(8, dtype=np.int64)})
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}", str(tmp_path)
    srv.shutdown()
    ev._reset_for_tests()


def _req(url, body=None, headers=None, method=None):
    req = urllib.request.Request(
        url, data=body.encode() if body is not None else None,
        headers=headers or {}, method=method)
    with urllib.request.urlopen(req) as r:
        return json.loads(r.read() or b"null"), dict(r.headers)


def _run_to_completion(base, payload):
    deadline = time.time() + 120
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.02)
        payload, _ = _req(payload["nextUri"])
    return payload


def test_trace_id_minted_and_correlated(server):
    base, tmp = server
    payload, headers = _req(f"{base}/v1/statement",
                            "SELECT SUM(a) AS s FROM t")
    tid = headers.get("X-DSQL-Trace")
    assert tid, "POST response missing the minted trace header"
    final = _run_to_completion(base, payload)
    assert final["data"] == [[28]]
    assert final["stats"]["traceId"] == tid       # wire stats surface
    # flight-recorder envelope carries the same ID
    from dask_sql_tpu.runtime import flight_recorder as fr
    envs = [e for e in fr.read_events(kind="query")
            if e.get("trace") == tid]
    assert envs and envs[0]["outcome"] == "ok"
    # ... and so do the bus events, begin through done
    from dask_sql_tpu.runtime import events as ev
    types = {e["type"] for e in ev._read_file(
        os.path.join(tmp, "events.jsonl")) if e.get("trace") == tid}
    assert {"query.begin", "query.done"} <= types


def test_client_supplied_trace_id_roundtrips(server):
    base, _ = server
    payload, headers = _req(f"{base}/v1/statement", "SELECT 1 AS one",
                            headers={"X-DSQL-Trace": "client-chosen-42"})
    assert headers.get("X-DSQL-Trace") == "client-chosen-42"
    final = _run_to_completion(base, payload)
    assert final["stats"]["traceId"] == "client-chosen-42"


def test_invalid_client_trace_id_is_replaced(server):
    base, _ = server
    _, headers = _req(f"{base}/v1/statement", "SELECT 1 AS one",
                      headers={"X-DSQL-Trace": "bad id;DROP"})
    tid = headers.get("X-DSQL-Trace")
    assert tid and tid != "bad id;DROP" and len(tid) == 16


def test_error_path_carries_trace_header(server):
    base, _ = server
    payload, headers = _req(f"{base}/v1/statement",
                            "SELECT nosuchcolumn FROM t",
                            headers={"X-DSQL-Trace": "err-trace-1"})
    assert headers.get("X-DSQL-Trace") == "err-trace-1"
    final = _run_to_completion(base, payload)
    assert "error" in final
    # unknown-id status poll still answers with a header (no info row)
    try:
        _req(f"{base}/v1/status/not-a-real-id")
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
        assert e.headers.get("X-DSQL-Trace") is None  # nothing to echo


def test_events_endpoint_streams_with_cursor(server):
    base, _ = server
    payload, headers = _req(f"{base}/v1/statement", "SELECT MAX(a) FROM t")
    _run_to_completion(base, payload)
    req = urllib.request.Request(f"{base}/v1/events?cursor=0&limit=1000")
    with urllib.request.urlopen(req) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        cursor = int(r.headers["X-DSQL-Cursor"])
        lines = [json.loads(ln) for ln in r.read().splitlines() if ln]
    assert cursor > 0
    assert any(e["type"] == "query.done" for e in lines)
    assert all(e["seq"] <= cursor for e in lines)
    # resuming at the returned cursor yields nothing new
    with urllib.request.urlopen(
            f"{base}/v1/events?cursor={cursor}") as r:
        assert r.read() == b""
        assert int(r.headers["X-DSQL-Cursor"]) == cursor


def test_trace_correlates_across_processes(server):
    """The acceptance proof: a CHILD process runs a query with a pinned
    DSQL_TRACE_ID against the SHARED history/events files; this process
    then joins the envelope and the events ring on that one ID."""
    base, tmp = server
    code = (
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [10, 20, 30]})\n"
        "assert c.sql('SELECT SUM(a) AS s FROM t').to_pylist() == [[60]]\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", DSQL_TIERED="0",
               DSQL_MAX_CONCURRENT_QUERIES="0", DSQL_RESULT_CACHE_MB="0",
               DSQL_TRACE_ID="xproc-trace-7")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()

    from dask_sql_tpu.runtime import events as ev
    from dask_sql_tpu.runtime import flight_recorder as fr
    envs = [e for e in fr.read_events(kind="query")
            if e.get("trace") == "xproc-trace-7"]
    assert len(envs) == 1 and envs[0]["pid"] != os.getpid()
    recs = [e for e in ev._read_file(os.path.join(tmp, "events.jsonl"))
            if e.get("trace") == "xproc-trace-7"]
    types = {e["type"] for e in recs}
    assert {"query.begin", "query.done"} <= types
    assert all(e["pid"] != os.getpid() for e in recs)
    # the same join through SQL: system.events rows carry the child's ID
    from dask_sql_tpu.context import Context
    c = Context()
    rows = c.sql("SELECT count(*) AS n FROM system.events "
                 "WHERE trace = 'xproc-trace-7'").to_pylist()
    assert rows[0][0] >= 2


def test_engine_snapshot_has_slo_section(server):
    base, _ = server
    payload, _ = _req(f"{base}/v1/statement", "SELECT COUNT(*) FROM t")
    _run_to_completion(base, payload)
    snap, _ = _req(f"{base}/v1/engine")
    slo = snap["slo"]
    assert slo["enabled"] is True
    classes = {r["class"]: r for r in slo["classes"]}
    assert classes["interactive"]["total"] >= 1
    assert isinstance(slo["anomalies"], list)
    assert slo["bus"]["seq"] > 0


def test_disabled_server_has_no_trace_surface(tmp_path, monkeypatch):
    """DSQL_EVENTS off: no headers, no stats field, /v1/events is the
    generic 404 — the wire is bit-identical to pre-watchtower."""
    monkeypatch.delenv("DSQL_EVENTS", raising=False)
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table("t", {"a": np.arange(4, dtype=np.int64)})
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    base = f"http://127.0.0.1:{srv.server_port}"
    try:
        payload, headers = _req(f"{base}/v1/statement",
                                "SELECT SUM(a) AS s FROM t",
                                headers={"X-DSQL-Trace": "ignored"})
        assert "X-DSQL-Trace" not in headers
        final = _run_to_completion(base, payload)
        assert final["data"] == [[6]]
        assert "traceId" not in final["stats"]
        try:
            _req(f"{base}/v1/events")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.shutdown()
