"""Failure-domain recovery (ISSUE 6 tentpole proofs).

**Stage replay.**  With ``stage_exec:2`` injected on a >=3-stage TPC-H
query, the retry re-executes exactly ONE stage: the ``stage_execs``
counter shows N+1 total stage executions (not 2N), the replay counters
fire, stages below the failed one are never re-run, and the answer still
matches the eager oracle.

**Cross-process quarantine.**  A plan whose compile FATALs in "process A"
is served via the eager fallback immediately — no compile attempt — in a
fresh "process B" sharing the quarantine file (process B modeled by
clearing every in-process compiled cache; the store's file is the only
carrier).  After expiry a half-open probe re-attempts the compile and a
success lifts the verdict.
"""
import os

import pandas as pd
import pytest

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context
from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import faults, quarantine as Q
from dask_sql_tpu.runtime import resilience as R
from tests.conftest import assert_eq

_needs_compiled = pytest.mark.skipif(
    os.environ.get("DSQL_COMPILE") == "0",
    reason="stage replay / quarantine live on the compiled path")

AGG_Q = "SELECT user_id, SUM(b) AS sb FROM user_table_1 GROUP BY user_id"


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()
    faults.reset()
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "1")
    monkeypatch.delenv("DSQL_QUARANTINE_FILE", raising=False)
    monkeypatch.delenv("DSQL_COMPILE_WATCHDOG_S", raising=False)
    yield
    faults.reset()


def _eager_oracle(c, query) -> pd.DataFrame:
    prev = os.environ.get("DSQL_COMPILE")
    os.environ["DSQL_COMPILE"] = "0"
    try:
        return c.sql(query, return_futures=False)
    finally:
        if prev is None:
            del os.environ["DSQL_COMPILE"]
        else:
            os.environ["DSQL_COMPILE"] = prev


# ---------------------------------------------------------------------------
# checkpointed stage replay
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tpch_ctx():
    data = generate_tpch(0.002)
    ctx = Context()
    for name, df in data.items():
        ctx.create_table(name, df)
    return ctx, data


@_needs_compiled
def test_stage_replay_reexecutes_exactly_one_stage(tpch_ctx, monkeypatch):
    """The acceptance proof: stage k fails transiently once; the retry
    re-runs ONLY stage k from the already-materialized boundary temps."""
    from benchmarks.pandas_tpch import q3 as _pandas_q3

    tpch_ctx, data = tpch_ctx
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    monkeypatch.setenv("DSQL_COMPILE_WORKERS", "1")   # deterministic order
    q = QUERIES[3]                                    # 3 heavy nodes: >=3 stages
    expected = _pandas_q3(data)                       # pandas oracle

    c0 = dict(compiled.stats)
    with faults.inject("stage_exec:2"):
        got = tpch_ctx.sql(q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)

    graphs = compiled.stats["stage_graphs"] - c0["stage_graphs"]
    assert graphs >= 1, "plan did not stage"
    n_stages = compiled.stats["stage_execs"] - c0["stage_execs"]
    # one injected failure -> exactly ONE extra stage execution: N+1, not 2N
    assert compiled.stats["fault_stage_exec"] - c0["fault_stage_exec"] == 1
    assert compiled.stats["stage_replays"] - c0["stage_replays"] == 1
    n_distinct = n_stages - 1                          # N attempts + 1 replay
    assert n_distinct >= 3, f"want >=3 stages, saw {n_distinct}"
    # the failed stage was the 2nd: exactly one completed stage was saved
    saved = (compiled.stats["stage_replay_saved_stages"]
             - c0["stage_replay_saved_stages"])
    assert saved == 1
    # no degradations: the graph never fell back to eager
    assert compiled.stats["degradations"] == c0["degradations"]


@_needs_compiled
def test_stage_replay_of_root_saves_all_materialized_deps(c, monkeypatch):
    """Failing the LAST stage preserves every dependency's output."""
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    monkeypatch.setenv("DSQL_COMPILE_WORKERS", "1")
    q = ("SELECT u1.user_id, SUM(u2.c) AS s FROM user_table_1 u1 "
         "JOIN user_table_2 u2 ON u1.user_id = u2.user_id "
         "GROUP BY u1.user_id")
    expected = _eager_oracle(c, q)
    c0 = dict(compiled.stats)
    # the 2-heavy-node plan stages into 2; fail the second (root) attempt
    with faults.inject("stage_exec:2"):
        got = c.sql(q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["stage_replays"] - c0["stage_replays"] == 1
    assert (compiled.stats["stage_replay_saved_stages"]
            - c0["stage_replay_saved_stages"]) == 1
    sch = c.schema.get("__split__")
    assert sch is None or not sch.tables, "leaked __split__ temps"


@_needs_compiled
def test_sabotaged_replay_still_degrades_cleanly(c, monkeypatch):
    """A fault on the replay path itself (the new stage_replay site) walks
    the ordinary ladder: the graph degrades to eager, answer correct."""
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    q = ("SELECT u1.user_id, SUM(u2.c) AS s FROM user_table_1 u1 "
         "JOIN user_table_2 u2 ON u1.user_id = u2.user_id "
         "GROUP BY u1.user_id")
    expected = _eager_oracle(c, q)
    d0 = compiled.stats["degradations"]
    with faults.inject("stage_exec:1+,stage_replay:1+"):
        got = c.sql(q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["degradations"] >= d0 + 1
    assert compiled.stats["fault_stage_replay"] >= 1


# ---------------------------------------------------------------------------
# cross-process quarantine
# ---------------------------------------------------------------------------

def _fresh_process():
    """Model a process restart: every in-memory verdict dies; only the
    quarantine FILE (and the catalog data) survives."""
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()


@_needs_compiled
def test_fatal_compile_quarantines_across_processes(c, tmp_path,
                                                    monkeypatch):
    qfile = str(tmp_path / "quarantine.json")
    monkeypatch.setenv("DSQL_QUARANTINE_FILE", qfile)
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "3600")
    expected = _eager_oracle(c, AGG_Q)

    # process A: the compile FATALs -> eager answer, exiled, verdict persisted
    e0 = compiled.stats["exiled"]
    with faults.inject("compile:1+:fatal"):
        got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["exiled"] == e0 + 1
    assert os.path.exists(qfile)
    entries = Q.QuarantineStore(qfile).entries()
    assert entries and all(v["verdict"] == "fatal" for v in entries.values())

    # process B (fresh caches, same file, fault GONE): served eager
    # immediately — zero compile attempts
    _fresh_process()
    n0, s0 = compiled.stats["compiles"], compiled.stats["quarantine_skips"]
    got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["compiles"] == n0, "quarantined plan was compiled"
    assert compiled.stats["quarantine_skips"] == s0 + 1

    # after expiry: ONE half-open probe re-attempts the compile; the fixed
    # engine compiles fine and the verdict is lifted.  Expiry is baked
    # into the persisted entry at mark time, so "time passing" is modeled
    # by rewinding the file's expires_at.
    import json as _json
    with open(qfile) as f:
        data = _json.load(f)
    for v in data.values():
        v["expires_at"] = 0.0
    with open(qfile, "w") as f:
        _json.dump(data, f)
    _fresh_process()
    p0 = compiled.stats["quarantine_probes"]
    got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["compiles"] == n0 + 1, "probe did not compile"
    assert compiled.stats["quarantine_probes"] == p0 + 1
    assert Q.QuarantineStore(qfile).entries() == {}, "verdict not lifted"

    # and the un-quarantined program serves from cache from now on
    h0 = compiled.stats["hits"]
    got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["hits"] == h0 + 1


@_needs_compiled
def test_transient_compile_failure_never_quarantines(c, tmp_path,
                                                     monkeypatch):
    """Transient means exactly that: exhausted transient retries degrade
    but leave NO cross-process verdict behind."""
    qfile = str(tmp_path / "quarantine.json")
    monkeypatch.setenv("DSQL_QUARANTINE_FILE", qfile)
    with faults.inject("compile:1+"):
        c.sql(AGG_Q, return_futures=False)
    assert Q.QuarantineStore(qfile).entries() == {}


@_needs_compiled
def test_watchdog_marks_wedged_compile(c, tmp_path, monkeypatch):
    """A compile stalled past DSQL_COMPILE_WATCHDOG_S gets its fingerprint
    marked suspect by the MONITOR thread (no cooperative checkpoint
    involved), and a 'fresh process' then skips the compile."""
    qfile = str(tmp_path / "quarantine.json")
    monkeypatch.setenv("DSQL_QUARANTINE_FILE", qfile)
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "3600")
    monkeypatch.setenv("DSQL_COMPILE_WATCHDOG_S", "0.2")
    expected = _eager_oracle(c, AGG_Q)
    t0 = compiled.stats["watchdog_trips"]
    # the stall sits between maybe_fail (inside the watched section's
    # retry loop) — sleep 900 ms >> 200 ms budget, then the fault raises
    # transiently and the ladder answers eager (retries exhausted)
    monkeypatch.setenv("DSQL_RETRY_MAX", "0")
    with faults.inject("compile:1+:sleep=900"):
        got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["watchdog_trips"] > t0
    entries = Q.QuarantineStore(qfile).entries()
    assert entries and any(v["verdict"] == "hang" for v in entries.values())
    _fresh_process()
    n0 = compiled.stats["compiles"]
    got = c.sql(AGG_Q, return_futures=False)
    assert_eq(got, expected, check_row_order=False)
    assert compiled.stats["compiles"] == n0, "hang-marked plan recompiled"
