"""Out-of-HBM streaming execution (physical/streaming.py + io/chunked.py).

The reference's execution is out-of-core by construction (partitioned dask
dataframes, /root/reference/dask_sql/input_utils/convert.py:38-62); here the
equivalence under test is: a table registered ``chunked=True`` must produce
the same answers as the resident path while holding at most one batch on
device, with one compile for all batches (shared dictionaries + fixed batch
shapes).
"""
import os

import numpy as np
import pandas as pd
import pytest

from benchmarks.tpch import QUERIES, generate_tpch
from dask_sql_tpu import Context
from dask_sql_tpu.physical import compiled
from dask_sql_tpu.physical.streaming import StreamingUnsupported


@pytest.fixture(scope="module")
def tpch_pair():
    data = generate_tpch(0.01, seed=5)
    plain = Context()
    ck = Context()
    for name, frame in data.items():
        plain.create_table(name, frame)
        if name == "lineitem":
            ck.create_table(name, frame, chunked=True, batch_rows=16384)
        else:
            ck.create_table(name, frame)
    return plain, ck, data


def _assert_frames(a, b):
    a = a.reset_index(drop=True)
    b = b.reset_index(drop=True)
    for col in a.columns:
        if pd.api.types.is_float_dtype(a[col]):
            a[col] = a[col].astype(np.float64).round(6)
            b[col] = b[col].astype(np.float64).round(6)
    cols = list(a.columns)
    pd.testing.assert_frame_equal(a.sort_values(cols, ignore_index=True),
                                  b.sort_values(cols, ignore_index=True),
                                  check_dtype=False, rtol=1e-5, atol=1e-6)


# ALL 22 TPC-H queries with lineitem chunked (VERDICT item 5: the reference
# runs every query out-of-core).  Queries not touching lineitem (2, 11, 13,
# 16, 22) run the ordinary resident path — the point is that registering the
# big table chunked never changes any answer.  Iterative subtree lowering
# covers the multi-scan shapes: Q17 reads lineitem twice, Q21 three times,
# Q4/Q21/Q22 need the semi/anti key-set strategy, Q18's inner groupby is
# high-cardinality.
@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_chunked_matches_resident(tpch_pair, qid):
    plain, ck, _ = tpch_pair
    want = plain.sql(QUERIES[qid], return_futures=False)
    got = ck.sql(QUERIES[qid], return_futures=False)
    _assert_frames(want, got)


@pytest.mark.skipif(os.environ.get("DSQL_COMPILE") == "0",
                    reason="asserts compiled-path batch reuse")
def test_batches_share_one_compiled_program(tpch_pair):
    _, ck, data = tpch_pair
    n_batches = (len(data["lineitem"]) + 16383) // 16384
    assert n_batches >= 3  # the test must actually exercise multi-batch
    before = dict(compiled.stats)
    ck.sql(QUERIES[6], return_futures=False)
    d = {k: compiled.stats[k] - before[k] for k in before}
    # one compile for the first batch (plus possibly the tiny merge plan);
    # every further batch must HIT the program cache
    assert d["hits"] >= n_batches - 1, d
    assert d["compiles"] <= 2, d


def test_chunked_parquet_roundtrip(tmp_path):
    df = pd.DataFrame({
        "g": ["x", "y", "z", "x"] * 700,
        "v": np.arange(2800, dtype=np.float64),
        "k": np.arange(2800) % 13,
    })
    path = str(tmp_path / "t.parquet")
    df.to_parquet(path, index=False, row_group_size=512)
    c = Context()
    c.create_table("t", path, chunked=True, batch_rows=1000)
    entry = c.schema["root"].tables["t"]
    assert entry.chunked.n_batches == 3  # 2800 rows / 1000, re-batched
    got = c.sql("SELECT g, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY g "
                "ORDER BY g", return_futures=False)
    exp = (df.groupby("g").agg(s=("v", "sum"), n=("v", "count"))
             .reset_index())
    np.testing.assert_allclose(got["s"], exp["s"])
    np.testing.assert_array_equal(got["n"], exp["n"])


def test_streaming_distinct_aggregate(tpch_pair):
    # DISTINCT aggregates stream as per-batch dedup (r2 gap, VERDICT item 5)
    plain, ck, _ = tpch_pair
    q = ("SELECT l_returnflag, COUNT(DISTINCT l_suppkey) AS n "
         "FROM lineitem GROUP BY l_returnflag")
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))
    q2 = "SELECT COUNT(DISTINCT l_suppkey) AS n FROM lineitem"
    _assert_frames(plain.sql(q2, return_futures=False),
                   ck.sql(q2, return_futures=False))


def test_streaming_rejects_unmergeable_shapes(tpch_pair):
    _, ck, _ = tpch_pair
    with pytest.raises(StreamingUnsupported, match="DISTINCT"):
        # a DISTINCT mixed with a plain SUM cannot share one dedup stream
        ck.sql("SELECT COUNT(DISTINCT l_suppkey) AS n, SUM(l_quantity) AS s "
               "FROM lineitem")
    with pytest.raises(StreamingUnsupported, match="no aggregate or LIMIT"):
        ck.sql("SELECT l_orderkey FROM lineitem WHERE l_quantity > 1")


def test_streaming_null_group_keys():
    df = pd.DataFrame({"g": ["a", None, "a", None, "b"] * 200,
                       "v": np.arange(1000, dtype=np.float64)})
    plain = Context()
    plain.create_table("t", df)
    ck = Context()
    ck.create_table("t", df, chunked=True, batch_rows=128)
    q = "SELECT g, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY g"
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))


def test_chunked_parquet_categorical_dictionaries(tmp_path):
    """Dictionary-encoded parquet columns whose row-group dictionaries
    differ must be re-encoded against ONE global dictionary — per-batch
    categorical codes mixed with a shared dictionary would silently decode
    to wrong strings (r2 review finding)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    # two row groups with DIFFERENT dictionary orders for the same column
    t1 = pa.table({"g": pa.array(["b", "a", "b"] * 100).dictionary_encode(),
                   "v": pa.array(np.arange(300, dtype=np.float64))})
    t2 = pa.table({"g": pa.array(["c", "b"] * 150).dictionary_encode(),
                   "v": pa.array(np.arange(300, 600, dtype=np.float64))})
    path = str(tmp_path / "cat.parquet")
    with pq.ParquetWriter(path, t1.schema) as w:
        w.write_table(t1)
        w.write_table(t2)
    c = Context()
    c.create_table("t", path, chunked=True, batch_rows=150)
    got = c.sql("SELECT g, COUNT(*) AS n, SUM(v) AS s FROM t GROUP BY g "
                "ORDER BY g", return_futures=False)
    df = pd.DataFrame({"g": ["b", "a", "b"] * 100 + ["c", "b"] * 150,
                       "v": np.arange(600, dtype=np.float64)})
    exp = df.groupby("g").agg(n=("v", "count"), s=("v", "sum")).reset_index()
    np.testing.assert_array_equal(got["g"], exp["g"])
    np.testing.assert_array_equal(got["n"], exp["n"])
    np.testing.assert_allclose(got["s"], exp["s"])


def test_chunked_parquet_binary_column_global_dictionary(tmp_path):
    """Binary arrow columns convert to object values; without a global
    dictionary pass each piece got a LOCAL dictionary and merged batches
    decoded against piece 0's codes (r2 advisor finding — counts came back
    {aa:250, bb:350} instead of {aa:100, bb:350, cc:150})."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    g1 = [b"aa"] * 100 + [b"bb"] * 200
    g2 = [b"bb"] * 150 + [b"cc"] * 150
    t1 = pa.table({"g": pa.array(g1, type=pa.binary()),
                   "v": pa.array(np.arange(300, dtype=np.float64))})
    t2 = pa.table({"g": pa.array(g2, type=pa.binary()),
                   "v": pa.array(np.arange(300, 600, dtype=np.float64))})
    path = str(tmp_path / "bin.parquet")
    with pq.ParquetWriter(path, t1.schema) as w:
        w.write_table(t1)
        w.write_table(t2)
    c = Context()
    c.create_table("t", path, chunked=True, batch_rows=150)
    got = c.sql("SELECT g, COUNT(*) AS n FROM t GROUP BY g ORDER BY g",
                return_futures=False)
    assert got["n"].tolist() == [100, 350, 150]
    # bytes decode to str (not repr) so string literals match
    assert got["g"].tolist() == ["aa", "bb", "cc"]
    one = c.sql("SELECT COUNT(*) AS n FROM t WHERE g = 'aa'",
                return_futures=False)
    assert one["n"].tolist() == [100]


def test_high_cardinality_groupby_merges_on_host(tpch_pair, monkeypatch):
    """A group-by whose partials exceed the device budget merges on HOST
    (pandas over the accumulated partials) — the shape that would
    previously OOM out-of-HBM mode's own merge step (r2 weakness 7)."""
    from dask_sql_tpu.physical import streaming as sm

    plain, ck, _ = tpch_pair
    monkeypatch.setattr(sm, "PARTIAL_BYTES_BUDGET", 1024)
    # group by orderkey: ~ one group per 4 rows — partials ARE the table
    q = ("SELECT l_orderkey, SUM(l_quantity) AS s, COUNT(*) AS n, "
         "MIN(l_discount) AS mi FROM lineitem GROUP BY l_orderkey")
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))


def test_streaming_composes_with_mesh():
    """chunked=True under Context(mesh=): each uploaded batch row-shards
    over the mesh and the per-batch program runs as GSPMD — out-of-core AND
    distributed at once (VERDICT item 4)."""
    from dask_sql_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()
    if mesh.devices.size < 2:
        pytest.skip("needs a multi-device mesh")
    data = generate_tpch(0.01, seed=5)
    plain = Context()
    dist = Context(mesh=mesh)
    for name, frame in data.items():
        plain.create_table(name, frame)
        if name == "lineitem":
            dist.create_table(name, frame, chunked=True, batch_rows=16384)
        else:
            dist.create_table(name, frame)
    # 1: heavy groupby; 3: join above the stream + topk; 9: 6-table
    # snowflake (5/6 exercise nothing further and GSPMD compiles are slow)
    for qid in (1, 3, 9):
        want = plain.sql(QUERIES[qid], return_futures=False)
        got = dist.sql(QUERIES[qid], return_futures=False)
        _assert_frames(want, got)


def test_chunked_inside_scalar_subquery(tpch_pair):
    # r2 rejected this shape; the iterative lowering streams the subquery
    # plan first (TPC-H Q15's shape)
    plain, ck, _ = tpch_pair
    q = ("SELECT s_suppkey FROM supplier WHERE s_suppkey > "
         "(SELECT AVG(l_suppkey) FROM lineitem)")
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))


# ---------------------------------------------------------------------------
# out-of-core window functions (VERDICT r3 item 5): a window with
# PARTITION BY streams its input per batch, regroups rows into hash
# buckets of the partition keys, and runs the window resident per bucket
# (physical/streaming.py _stream_window_split).  The reference runs
# windows over partitioned input by construction
# (/root/reference/dask_sql/physical/rel/logical/window.py:207-414).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def window_pair():
    rng = np.random.RandomState(7)
    n = 3000
    df = pd.DataFrame({
        "k": rng.randint(0, 11, n),
        "s": rng.choice(["a", "b", "c", None], n),
        "v": np.round(rng.randn(n), 4),
        "w": rng.randint(-50, 50, n).astype(np.float64),
    })
    plain = Context()
    plain.create_table("t", df)
    ck = Context()
    ck.create_table("t", df, chunked=True, batch_rows=256)
    return plain, ck


WINDOW_QUERIES = {
    "row_number": (
        "SELECT k, v, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v, w) AS rn "
        "FROM t ORDER BY k, rn LIMIT 200"),
    "sum_over": (
        "SELECT k, SUM(v) OVER (PARTITION BY k ORDER BY v, w) AS c "
        "FROM t ORDER BY k, c LIMIT 200"),
    "rows_frame": (
        "SELECT k, SUM(w) OVER (PARTITION BY k ORDER BY v, w "
        "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS f "
        "FROM t ORDER BY k, f LIMIT 200"),
    "null_partition_keys": (
        "SELECT s, COUNT(*) OVER (PARTITION BY s) AS n, "
        "ROW_NUMBER() OVER (PARTITION BY s ORDER BY v, w) AS rn "
        "FROM t ORDER BY s, rn LIMIT 200"),
    "agg_above_window": (
        "SELECT k, MAX(rn) AS m, SUM(rs) AS t FROM (SELECT k, "
        "ROW_NUMBER() OVER (PARTITION BY k ORDER BY v, w) AS rn, "
        "SUM(v) OVER (PARTITION BY k) AS rs FROM t) x GROUP BY k "
        "ORDER BY k"),
}


@pytest.mark.parametrize("name", sorted(WINDOW_QUERIES))
def test_window_chunked_matches_resident(window_pair, name):
    plain, ck = window_pair
    q = WINDOW_QUERIES[name]
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))


def test_window_output_reregisters_as_chunked(window_pair, monkeypatch):
    """A window output larger than the partial budget re-registers as a
    chunked source (sliced back into batch_rows batches) so the aggregate
    above it KEEPS streaming instead of materializing a table-sized temp."""
    from dask_sql_tpu.physical import streaming as sm

    plain, ck = window_pair
    monkeypatch.setattr(sm, "PARTIAL_BYTES_BUDGET", 1024)
    q = WINDOW_QUERIES["agg_above_window"]
    _assert_frames(plain.sql(q, return_futures=False),
                   ck.sql(q, return_futures=False))


def test_window_without_partition_rejected(window_pair):
    _, ck = window_pair
    with pytest.raises(StreamingUnsupported, match="PARTITION BY"):
        ck.sql("SELECT k, SUM(v) OVER (ORDER BY v) AS c FROM t")


def test_window_partition_skew_warns(caplog):
    """One giant partition defeats the per-bucket memory bound; the result
    stays correct but the weakened bound must be LOUD (no silent caps)."""
    import logging

    n = 600
    df = pd.DataFrame({"k": np.zeros(n, dtype=np.int64),
                       "v": np.arange(n, dtype=np.float64)})
    plain = Context()
    plain.create_table("t", df)
    ck = Context()
    ck.create_table("t", df, chunked=True, batch_rows=100)
    q = ("SELECT k, SUM(v) OVER (PARTITION BY k ORDER BY v) AS c "
         "FROM t ORDER BY c LIMIT 50")
    with caplog.at_level(logging.WARNING,
                         logger="dask_sql_tpu.physical.streaming"):
        got = ck.sql(q, return_futures=False)
    _assert_frames(plain.sql(q, return_futures=False), got)
    assert any("partition skew" in r.message for r in caplog.records)


def test_window_streaming_composes_with_mesh():
    from dask_sql_tpu.parallel.mesh import default_mesh

    mesh = default_mesh()
    if mesh.devices.size < 2:
        pytest.skip("needs a multi-device mesh")
    rng = np.random.RandomState(11)
    n = 1200
    df = pd.DataFrame({"k": rng.randint(0, 5, n),
                       "v": np.round(rng.randn(n), 4)})
    plain = Context()
    plain.create_table("t", df)
    dist = Context(mesh=mesh)
    dist.create_table("t", df, chunked=True, batch_rows=256)
    q = ("SELECT k, MAX(rn) AS m FROM (SELECT k, ROW_NUMBER() OVER "
         "(PARTITION BY k ORDER BY v) AS rn FROM t) x GROUP BY k ORDER BY k")
    _assert_frames(plain.sql(q, return_futures=False),
                   dist.sql(q, return_futures=False))
