"""Integration tests for the workload manager (runtime/scheduler.py)
driving the real server and Context: saturating bursts answer 429 +
``Retry-After`` without losing queries, admission telemetry reconciles with
outcomes, wire stats carry the scheduler's live measurements, and injected
``admission`` faults degrade into the typed-error machinery."""
import json
import threading
import time
import urllib.error
import urllib.request

import pandas as pd
import pytest

from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import scheduler as sched
from dask_sql_tpu.runtime import telemetry as tel

_SCHED_COUNTERS = tuple(f"sched_{kind}_{p}"
                        for kind in ("admitted", "rejected", "timeout")
                        for p in sched.PRIORITIES)


def _snapshot():
    return {k: tel.REGISTRY.get(k) for k in _SCHED_COUNTERS}


def _delta(before):
    now = _snapshot()
    return {k: now[k] - before[k] for k in before}


@pytest.fixture()
def server(monkeypatch):
    """A server over a saturable scheduler: 1 slot, 1 queue position."""
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "1")
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "1")
    monkeypatch.setenv("DSQL_QUEUE_TIMEOUT_MS", "60000")
    monkeypatch.setenv("DSQL_SERVER_WORKERS", "2")
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.server.app import run_server

    context = Context()
    context.create_table("df", pd.DataFrame({"a": list(range(2000))}))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def _post(url, body, headers=None):
    """(status, headers, payload) — 429s come back as HTTPError."""
    req = urllib.request.Request(url, data=body.encode(), method="POST",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _poll(server, payload, timeout=60):
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        with urllib.request.urlopen(payload["nextUri"]) as r:
            payload = json.loads(r.read())
    return payload


def test_saturating_burst_429_no_query_lost(server):
    """A burst beyond slots+depth: the excess is rejected immediately with
    429 + Retry-After, everything admitted completes correctly, and the
    per-class admission counters reconcile with the outcomes."""
    before = _snapshot()
    results, lock = [], threading.Lock()

    def go(i):
        # distinct literals -> distinct programs: each admitted query
        # holds its slot through a real compile, keeping the system
        # saturated long enough for the burst to overflow the queue
        status, headers, payload = _post(
            f"{server}/v1/statement",
            f"SELECT SUM(a + {i}) AS s FROM df",
            {"X-DSQL-Priority": "batch"})
        with lock:
            results.append((i, status, headers, payload))

    threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)

    rejected = [r for r in results if r[1] == 429]
    accepted = [r for r in results if r[1] == 200]
    assert len(rejected) + len(accepted) == 6
    # 1 slot + 1 queue position + in-flight slack: the burst MUST overflow
    assert rejected, "burst never produced a 429"
    for _, _, headers, payload in rejected:
        assert int(headers["Retry-After"]) >= 1
        err = payload["error"]
        assert err["errorType"] == "INSUFFICIENT_RESOURCES"
        assert err["errorName"] == "QUERY_QUEUE_FULL"

    # no accepted query is lost: each polls to FINISHED with the right sum
    expected_base = sum(range(2000))
    for i, _, _, payload in accepted:
        final = _poll(server, payload)
        assert "error" not in final, final.get("error")
        assert final["stats"]["state"] == "FINISHED"
        assert final["data"] == [[expected_base + 2000 * i]]
        assert final["stats"]["queuedTimeMillis"] >= 0

    d = _delta(before)
    assert d["sched_admitted_batch"] == len(accepted)
    assert d["sched_rejected_batch"] == len(rejected)
    assert d["sched_timeout_batch"] == 0


def test_wire_stats_report_live_scheduler_gauges(server):
    status, _, payload = _post(f"{server}/v1/statement",
                               "SELECT COUNT(*) AS n FROM df")
    assert status == 200
    final = _poll(server, payload)
    stats = final["stats"]
    assert stats["state"] == "FINISHED"
    # live gauges, not the old per-query 0/1 constants: idle after the
    # query, both report the true process-wide state
    assert stats["queuedSplits"] == 0
    assert stats["runningSplits"] >= 0
    assert stats["queuedTimeMillis"] >= 0
    # the queued phase is part of the per-query phase breakdown
    assert "queued" in stats["phaseMillis"]


def test_priority_header_lands_in_class_counters(server):
    before = _snapshot()
    status, _, payload = _post(f"{server}/v1/statement",
                               "SELECT MAX(a) AS m FROM df",
                               {"X-DSQL-Priority": "background"})
    assert status == 200
    final = _poll(server, payload)
    assert final["stats"]["state"] == "FINISHED"
    assert _delta(before)["sched_admitted_background"] == 1


def test_unknown_priority_header_falls_back(server):
    before = _snapshot()
    status, _, payload = _post(f"{server}/v1/statement",
                               "SELECT MIN(a) AS m FROM df",
                               {"X-DSQL-Priority": "no-such-class"})
    assert status == 200
    final = _poll(server, payload)
    assert "error" not in final
    assert _delta(before)["sched_admitted_interactive"] == 1


def test_admission_fault_degrades_cleanly(server):
    """An injected admission fault fails THAT query with the typed
    transient verdict (no slot leaked, no wedged queue) and the very next
    query sails through."""
    before = tel.REGISTRY.get("fault_admission")
    with faults.inject("admission:1"):
        status, _, payload = _post(f"{server}/v1/statement",
                                   "SELECT SUM(a) AS s FROM df")
        assert status == 200            # POST is accepted; execution fails
        final = _poll(server, payload)
        assert final["error"]["errorName"] == "FAULT_INJECTED"
    assert tel.REGISTRY.get("fault_admission") == before + 1
    mgr = sched.get_manager()
    assert mgr.running_count() == 0 and mgr.queue_depth() == 0
    status, _, payload = _post(f"{server}/v1/statement",
                               "SELECT SUM(a) AS s FROM df")
    final = _poll(server, payload)
    assert "error" not in final and final["stats"]["state"] == "FINISHED"


def test_server_workers_knob(monkeypatch):
    from dask_sql_tpu.server import app

    monkeypatch.setenv("DSQL_SERVER_WORKERS", "7")
    assert app._server_workers() == 7
    monkeypatch.delenv("DSQL_SERVER_WORKERS", raising=False)
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "3")
    assert app._server_workers() == 3    # default: the scheduler's limit
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "0")
    assert app._server_workers() == 4    # scheduler off: historical pool


def test_context_concurrency_bounded_and_complete(monkeypatch):
    """Direct Context.sql under contention: 6 threads through 2 slots all
    complete, each report carries a queued phase, and admissions reconcile."""
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "16")
    from dask_sql_tpu import Context

    c = Context()
    c.create_table("t", pd.DataFrame({"a": list(range(500))}))
    before = _snapshot()
    outs, reports, lock = {}, {}, threading.Lock()

    def go(i):
        out = c.sql(f"SELECT SUM(a + {i}) AS s FROM t",
                    return_futures=False, priority="batch")
        with lock:
            outs[i] = int(out["s"][0])
            reports[i] = tel.last_report()   # thread-local: race-free

    threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    base = sum(range(500))
    assert outs == {i: base + 500 * i for i in range(6)}
    for rep in reports.values():
        assert "queued" in rep.phases
    d = _delta(before)
    assert d["sched_admitted_batch"] == 6
    assert d["sched_rejected_batch"] == 0 and d["sched_timeout_batch"] == 0
    mgr = sched.get_manager()
    assert mgr.running_count() == 0 and mgr.queue_depth() == 0
