"""Golden semantics oracle: the type-sensitive edges SQLite cannot judge.

The reference double-oracles against PostgreSQL in docker
(/root/reference/tests/integration/fixtures.py:188-288, test_postgres.py)
precisely because SQLite is weak on NULL-ordering defaults, division,
date arithmetic and rounding.  No postgres exists in this image, so these
are GOLDEN tests: expected values derived from the SQL standard /
PostgreSQL semantics (or, where the reference's pandas substrate
intentionally diverges, from the reference's behavior — noted inline).
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context


@pytest.fixture()
def c():
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({
        "x": [3.0, 1.0, None, 2.0],
        "i": [-7, 7, 5, -5],
        "s": ["b", None, "a", "c"],
        "d": pd.to_datetime(["1994-01-31", "1994-03-15",
                             "1996-02-29", "1994-12-31"]),
    }))
    return ctx


def _col(ctx, sql, col=0):
    return ctx.sql(sql, return_futures=False).iloc[:, col].tolist()


class TestNullOrderingDefaults:
    """PostgreSQL/Calcite: NULLs sort as LARGER than every value — last
    under ASC, first under DESC (SQLite does the opposite for ASC, which is
    why it cannot judge this)."""

    def test_asc_default_nulls_last(self, c):
        got = c.sql("SELECT x FROM t ORDER BY x", return_futures=False)
        vals = got["x"].tolist()
        assert vals[:3] == [1.0, 2.0, 3.0] and pd.isna(vals[3])

    def test_desc_default_nulls_first(self, c):
        got = c.sql("SELECT x FROM t ORDER BY x DESC", return_futures=False)
        vals = got["x"].tolist()
        assert pd.isna(vals[0]) and vals[1:] == [3.0, 2.0, 1.0]

    def test_explicit_overrides(self, c):
        vals = _col(c, "SELECT x FROM t ORDER BY x ASC NULLS FIRST")
        assert pd.isna(vals[0]) and vals[1:] == [1.0, 2.0, 3.0]
        vals = _col(c, "SELECT x FROM t ORDER BY x DESC NULLS LAST")
        assert vals[:3] == [3.0, 2.0, 1.0] and pd.isna(vals[3])

    def test_string_nulls(self, c):
        vals = _col(c, "SELECT s FROM t ORDER BY s")
        assert vals[:3] == ["a", "b", "c"] and pd.isna(vals[3])


class TestDivisionSemantics:
    """SQL integer division truncates toward zero; MOD takes the sign of
    the dividend (PostgreSQL). SQLite agrees on these but returns NULL for
    x/0 where the standard raises — we follow the reference's pandas/IEEE
    substrate for float/0 (±inf, nan)."""

    def test_integer_division_truncates_toward_zero(self, c):
        assert _col(c, "SELECT -7/2 AS q") == [-3]
        assert _col(c, "SELECT 7/-2 AS q") == [-3]
        assert _col(c, "SELECT CAST(i/2 AS BIGINT) AS q FROM t") == [-3, 3, 2, -2]

    def test_mod_sign_of_dividend(self, c):
        assert _col(c, "SELECT MOD(-7, 2) AS m") == [-1]
        assert _col(c, "SELECT MOD(7, -2) AS m") == [1]
        assert _col(c, "SELECT MOD(i, 3) AS m FROM t") == [-1, 1, 2, -2]

    def test_float_division_by_zero_ieee(self, c):
        r = c.sql("SELECT 1/0.0 AS pinf, -1/0.0 AS ninf",
                  return_futures=False)
        assert np.isposinf(r["pinf"][0]) and np.isneginf(r["ninf"][0])

    def test_decimal_literal_division(self, c):
        # DECIMAL literals: scale preserved through division (f64 substrate)
        r = _col(c, "SELECT 0.3 / 0.1 AS q")
        assert abs(r[0] - 3.0) < 1e-12


class TestRoundingSemantics:
    """numpy/pandas half-even rounding — the REFERENCE's substrate
    (dask-sql lowers ROUND to the pandas/numpy round, mappings.py's f64
    DECIMAL compromise). PostgreSQL numeric would round half away from
    zero; the reference intentionally does not, and parity follows the
    reference."""

    def test_half_even(self, c):
        assert _col(c, "SELECT ROUND(0.5) AS r") == [0.0]
        assert _col(c, "SELECT ROUND(1.5) AS r") == [2.0]
        assert _col(c, "SELECT ROUND(2.5) AS r") == [2.0]
        assert _col(c, "SELECT ROUND(-0.5) AS r") == [-0.0]

    def test_round_to_digits(self, c):
        assert _col(c, "SELECT ROUND(1.234, 2) AS r") == [1.23]
        assert _col(c, "SELECT ROUND(x, 0) AS r FROM t WHERE x IS NOT NULL"
                    ) == [3.0, 1.0, 2.0]

    def test_ceil_floor(self, c):
        r = c.sql("SELECT CEIL(1.1) AS a, FLOOR(-1.1) AS b, CEIL(-1.1) AS c2,"
                  " FLOOR(1.9) AS d", return_futures=False)
        assert r.values.tolist() == [[2.0, -2.0, -1.0, 1.0]]


class TestDateArithmetic:
    """Month arithmetic clamps to month end (PostgreSQL: Jan 31 + 1 mon =
    Feb 28); leap years honored; intervals compose."""

    def test_add_month_clamps(self, c):
        got = _col(c, "SELECT d + INTERVAL '1' MONTH AS m FROM t")
        assert [str(v)[:10] for v in got] == [
            "1994-02-28", "1994-04-15", "1996-03-29", "1995-01-31"]

    def test_add_year_leap_clamp(self, c):
        got = _col(c, "SELECT d + INTERVAL '1' YEAR AS y FROM t")
        # 1996-02-29 + 1 year -> 1997-02-28 (clamped, not Mar 1)
        assert str(got[2])[:10] == "1997-02-28"

    def test_day_interval_exact(self, c):
        got = _col(c, "SELECT d + INTERVAL '60' DAY AS y FROM t")
        assert str(got[0])[:10] == "1994-04-01"

    def test_extract_fields(self, c):
        r = c.sql("SELECT EXTRACT(YEAR FROM d) AS y, EXTRACT(MONTH FROM d) "
                  "AS m, EXTRACT(DAY FROM d) AS dd, EXTRACT(QUARTER FROM d) "
                  "AS q FROM t", return_futures=False)
        assert r["y"].tolist() == [1994, 1994, 1996, 1994]
        assert r["m"].tolist() == [1, 3, 2, 12]
        assert r["dd"].tolist() == [31, 15, 29, 31]
        assert r["q"].tolist() == [1, 1, 1, 4]

    def test_date_comparison_boundary(self, c):
        # DATE literal vs timestamp comparison at midnight boundary
        got = _col(c, "SELECT COUNT(*) AS n FROM t "
                      "WHERE d >= DATE '1994-03-15'")
        assert got == [3]


class TestAggregateEdges:
    """Aggregates over zero rows: SUM/AVG/MIN/MAX -> NULL, COUNT -> 0
    (standard; both oracles agree, pinned here because the compiled path
    short-circuits empty groups differently)."""

    def test_global_aggregates_over_empty(self, c):
        r = c.sql("SELECT SUM(x) AS s, AVG(x) AS a, MIN(x) AS mn, "
                  "MAX(x) AS mx, COUNT(x) AS cnt, COUNT(*) AS n "
                  "FROM t WHERE x > 100", return_futures=False)
        assert pd.isna(r["s"][0]) and pd.isna(r["a"][0])
        assert pd.isna(r["mn"][0]) and pd.isna(r["mx"][0])
        assert r["cnt"][0] == 0 and r["n"][0] == 0

    def test_aggregates_skip_nulls(self, c):
        r = c.sql("SELECT SUM(x) AS s, COUNT(x) AS cx, COUNT(*) AS n, "
                  "AVG(x) AS a FROM t", return_futures=False)
        assert r["s"][0] == 6.0 and r["cx"][0] == 3
        assert r["n"][0] == 4 and abs(r["a"][0] - 2.0) < 1e-12

    def test_sum_all_nulls_is_null(self, c):
        r = c.sql("SELECT SUM(x) AS s FROM t WHERE x IS NULL",
                  return_futures=False)
        assert pd.isna(r["s"][0])


class TestThreeValuedLogic:
    def test_null_comparisons_are_unknown(self, c):
        # x <> NULL is UNKNOWN -> filtered; NOT(UNKNOWN) is UNKNOWN too
        assert _col(c, "SELECT COUNT(*) AS n FROM t WHERE x <> 99") == [3]
        assert _col(c, "SELECT COUNT(*) AS n FROM t "
                       "WHERE NOT (x <> 99)") == [0]

    def test_and_or_with_unknown(self, c):
        # UNKNOWN OR TRUE = TRUE; UNKNOWN AND TRUE = UNKNOWN (filtered)
        assert _col(c, "SELECT COUNT(*) AS n FROM t "
                       "WHERE x > 0 OR i > 0") == [4]
        assert _col(c, "SELECT COUNT(*) AS n FROM t "
                       "WHERE x > 0 AND i < 10") == [3]

    def test_not_in_with_null_in_list(self, c):
        # i NOT IN (5, NULL): never TRUE for non-matching rows (UNKNOWN)
        assert _col(c, "SELECT COUNT(*) AS n FROM t "
                       "WHERE i NOT IN (5, CAST(NULL AS BIGINT))") == [0]
