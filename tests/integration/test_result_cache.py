"""Integration tests for the result & subplan cache: end-to-end hits,
DDL invalidation (unit + server round-trip), stage-boundary subplan reuse
across overlapping queries, fault-injected population, and the EXPLAIN
ANALYZE / wire-stat surfaces."""
import json
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import result_cache as rc
from dask_sql_tpu.runtime import telemetry as tel

from tests.conftest import assert_eq, needs_compiled


@pytest.fixture(autouse=True)
def _armed_cache(monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "64")
    rc.get_cache().clear()
    yield
    rc.get_cache().clear()


def _ctx(seed=1, n=200):
    rng = np.random.RandomState(seed)
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({
        "k": rng.randint(0, 5, n), "v": rng.randint(0, 100, n)}))
    return ctx


Q = "SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k"


def test_repeated_query_hits_and_matches(c):
    q = "SELECT user_id, SUM(b) AS sb FROM user_table_1 GROUP BY user_id"
    cold = c.sql(q, return_futures=False)
    assert c.last_report.cache["hit"] is False
    assert c.last_report.cache["stored"] is True
    warm = c.sql(q, return_futures=False)
    rep = c.last_report.cache
    assert rep["hit"] is True and rep["tier"] == "device"
    assert_eq(warm, cold)
    # phases: a hit executes nothing — no compile/materialize spans
    assert "compile" not in c.last_report.phases


def test_drop_and_recreate_never_serves_stale():
    ctx = _ctx(seed=1)
    old = ctx.sql(Q, return_futures=False)
    ctx.sql("DROP TABLE t")
    rng = np.random.RandomState(99)
    ctx.create_table("t", pd.DataFrame({
        "k": rng.randint(0, 5, 200), "v": rng.randint(1000, 2000, 200)}))
    new = ctx.sql(Q, return_futures=False)
    assert ctx.last_report.cache["hit"] is False
    assert not new["s"].equals(old["s"])
    # and the recomputed answer is right
    expected = (ctx.schema["root"].tables["t"].table.to_pandas()
                .groupby("k", as_index=False)["v"].sum()
                .rename(columns={"v": "s"}))
    assert_eq(new, expected)


def test_create_or_replace_table_as_invalidates():
    ctx = _ctx(seed=1)
    ctx.sql("CREATE TABLE d AS SELECT k, v FROM t")
    q = "SELECT SUM(v) AS s FROM d"
    first = ctx.sql(q, return_futures=False)
    ctx.sql("CREATE OR REPLACE TABLE d AS SELECT k, v + 1 AS v FROM t")
    second = ctx.sql(q, return_futures=False)
    assert ctx.last_report.cache["hit"] is False
    assert int(second["s"][0]) == int(first["s"][0]) + 200


def test_volatile_query_never_cached():
    ctx = _ctx()
    ctx.sql("SELECT RAND() AS r FROM t", return_futures=False)
    rep = ctx.last_report.cache
    assert rep["hit"] is False and rep["stored"] is False


def test_failed_population_skips_store_not_query():
    ctx = _ctx()
    f0 = tel.REGISTRY.get("fault_cache_populate")
    with faults.inject("cache_populate:1"):
        first = ctx.sql(Q, return_futures=False)       # store sabotaged
        assert ctx.last_report.cache["stored"] is False
        assert tel.REGISTRY.get("fault_cache_populate") == f0 + 1
        second = ctx.sql(Q, return_futures=False)      # miss; store lands
        assert ctx.last_report.cache["hit"] is False
        third = ctx.sql(Q, return_futures=False)       # now a hit
        assert ctx.last_report.cache["hit"] is True
    assert_eq(second, first)
    assert_eq(third, first)


def test_deadline_exceeded_never_populates():
    from dask_sql_tpu.runtime import resilience as res

    ctx = _ctx()
    stores0 = tel.REGISTRY.get("result_cache_stores")
    with pytest.raises(res.DeadlineExceeded):
        ctx.sql(Q, timeout=1e-9)
    assert tel.REGISTRY.get("result_cache_stores") == stores0
    # the next (unbounded) run is a miss, not a stale/partial hit
    ctx.sql(Q, return_futures=False)
    assert ctx.last_report.cache["hit"] is False


@needs_compiled
def test_subplan_reuse_across_overlapping_queries(monkeypatch):
    """Two DIFFERENT queries sharing a join+aggregate subplan: with the
    stage budget forced to 1 the shared subtree becomes its own stage, and
    the second query replays its materialized output from the cache."""
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "1")
    rng = np.random.RandomState(3)
    ctx = Context()
    ctx.create_table("f", pd.DataFrame({
        "id": rng.randint(0, 50, 2000), "v": rng.randint(0, 10, 2000)}))
    ctx.create_table("d", pd.DataFrame({
        "id": np.arange(50), "w": rng.randint(0, 5, 50)}))
    shared = ("(SELECT f.id AS fid, SUM(f.v + d.w) AS sv FROM f "
              "JOIN d ON f.id = d.id GROUP BY f.id)")
    q1 = f"SELECT * FROM {shared} x WHERE sv > 10"
    q2 = f"SELECT * FROM {shared} x WHERE sv > 200"

    ctx.sql(q1, return_futures=False)
    sub0 = tel.REGISTRY.get("result_cache_subplan_hits")
    got = ctx.sql(q2, return_futures=False)
    rep = ctx.last_report.cache
    assert tel.REGISTRY.get("result_cache_subplan_hits") > sub0
    assert rep["subplan_hits"] >= 1
    assert rep["hit"] is False  # different full query: data reuse, not replay

    # equality against a cache-off recompute
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "0")
    expected = ctx.sql(q2, return_futures=False)
    assert_eq(got, expected, check_row_order=False)


def test_explain_analyze_reports_cache_state():
    ctx = _ctx()
    out = ctx.sql("EXPLAIN ANALYZE " + Q, return_futures=False)
    lines = list(out["PLAN"])
    assert any(l.startswith("-- cache: miss") for l in lines)
    # the analyzed run populated: a plain run now hits ...
    ctx.sql(Q, return_futures=False)
    assert ctx.last_report.cache["hit"] is True
    # ... and a second EXPLAIN ANALYZE sees the live entry
    out = ctx.sql("EXPLAIN ANALYZE " + Q, return_futures=False)
    assert any(l.startswith("-- cache: hit tier=device")
               for l in out["PLAN"])


# ---------------------------------------------------------------------------
# server round trip
# ---------------------------------------------------------------------------

@pytest.fixture()
def served_ctx():
    from dask_sql_tpu.server.app import run_server

    ctx = _ctx(seed=7)
    srv = run_server(context=ctx, host="127.0.0.1", port=0, blocking=False)
    yield ctx, f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()
    ctx.server = None


def _run(server, sql, timeout=30):
    req = urllib.request.Request(f"{server}/v1/statement",
                                 data=sql.encode(), method="POST")
    with urllib.request.urlopen(req) as r:
        payload = json.loads(r.read())
    deadline = time.time() + timeout
    while "nextUri" in payload and time.time() < deadline:
        time.sleep(0.05)
        with urllib.request.urlopen(payload["nextUri"]) as r:
            payload = json.loads(r.read())
    return payload


def test_server_round_trip_cache_hit_and_ddl_invalidation(served_ctx):
    ctx, server = served_ctx
    cold = _run(server, Q)
    assert cold["stats"]["cacheHit"] is False
    warm = _run(server, Q)
    assert warm["stats"]["cacheHit"] is True
    assert warm["stats"]["cacheTier"] == "device"
    assert warm["data"] == cold["data"]
    # DDL through the server: DROP + recreate with different data
    _run(server, "DROP TABLE t")
    rng = np.random.RandomState(8)
    ctx.create_table("t", pd.DataFrame({
        "k": rng.randint(0, 5, 200), "v": rng.randint(500, 600, 200)}))
    fresh = _run(server, Q)
    assert fresh["stats"]["cacheHit"] is False
    assert fresh["data"] != cold["data"]
    # /metrics exposes the cache counters + gauges
    with urllib.request.urlopen(f"{server}/metrics") as r:
        text = r.read().decode()
    assert "dsql_result_cache_hits_total" in text
    assert "# TYPE dsql_result_cache_bytes gauge" in text
