"""SHOW / DESCRIBE tests (reference: tests/integration/test_show.py)."""
import pandas as pd

from tests.conftest import assert_eq


def test_show_schemas(c):
    result = c.sql("SHOW SCHEMAS").to_pandas()
    assert "root" in list(result["Schema"])
    assert "information_schema" in list(result["Schema"])


def test_show_schemas_like(c):
    result = c.sql("SHOW SCHEMAS LIKE 'root'").to_pandas()
    assert list(result["Schema"]) == ["root"]


def test_show_tables(c):
    result = c.sql("SHOW TABLES FROM root").to_pandas()
    assert "df_simple" in list(result["Table"])
    assert "user_table_1" in list(result["Table"])


def test_show_columns(c):
    result = c.sql("SHOW COLUMNS FROM df_simple").to_pandas()
    assert list(result["Column"]) == ["a", "b"]
    assert list(result["Type"]) == ["bigint", "double"]


def test_describe(c):
    result = c.sql("DESCRIBE df_simple").to_pandas()
    assert list(result["Column"]) == ["a", "b"]


def test_analyze(c, df):
    result = c.sql(
        "ANALYZE TABLE df COMPUTE STATISTICS FOR ALL COLUMNS").to_pandas()
    stats = set(result["statistic"])
    assert "count" in stats and "mean" in stats and "data_type" in stats
    result2 = c.sql(
        "ANALYZE TABLE df COMPUTE STATISTICS FOR COLUMNS a").to_pandas()
    assert "a" in result2.columns and "b" not in result2.columns
