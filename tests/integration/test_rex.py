"""Scalar expression tests (reference: tests/integration/test_rex.py)."""
import numpy as np
import pandas as pd
import pytest

from tests.conftest import assert_eq


def test_case(c, df):
    result = c.sql(
        """SELECT
            CASE WHEN a = 3 THEN 1 END AS "S1",
            CASE WHEN a > 0 THEN a ELSE 1 END AS "S2",
            CASE WHEN a = 4 THEN 3 ELSE a + 1 END AS "S3",
            CASE WHEN a = 3 THEN 1 WHEN a > 0 THEN 2 ELSE a END AS "S4",
            CASE a WHEN 1 THEN 10 WHEN 2 THEN 20 ELSE 30 END AS "S5"
        FROM df""").to_pandas()
    a = df["a"]
    expected = pd.DataFrame({
        "S1": a.where(a == 3, np.nan).where(a != 3, 1.0),
        "S2": a.where(a > 0, 1),
        "S3": (a + 1).where(a != 4, 3),
        "S4": a.where(a != 3, 1).where((a == 3) | (a <= 0), 2),
        "S5": a.map({1: 10, 2: 20}).fillna(30),
    })
    assert_eq(result, expected)


def test_literal_null(c):
    result = c.sql("SELECT NULL AS n, 1 + NULL AS m").to_pandas()
    assert result["n"].isna().all()
    assert result["m"].isna().all()


def test_boolean_operations(c):
    frame = pd.DataFrame({"b": pd.array([True, False, None], dtype="boolean")})
    c.create_table("bools", frame)
    result = c.sql(
        """SELECT b IS TRUE AS t, b IS FALSE AS f, b IS NOT TRUE AS nt,
                  b IS NOT FALSE AS nf, b IS NULL AS i, NOT b AS n
           FROM bools""").to_pandas()
    assert list(result["t"]) == [True, False, False]
    assert list(result["f"]) == [False, True, False]
    assert list(result["nt"]) == [False, True, True]
    assert list(result["nf"]) == [True, False, True]
    assert list(result["i"]) == [False, False, True]
    assert result["n"][0] == False and result["n"][1] == True and pd.isna(result["n"][2])


def test_math_operations(c, df):
    result = c.sql(
        """SELECT ABS(b - 5) AS "abs", ROUND(b, 1) AS "round", FLOOR(b) AS "floor",
                  CEIL(b) AS "ceil", SQRT(b) AS "sqrt", SIGN(b - 5) AS "sign"
           FROM df""").to_pandas()
    b = df["b"]
    np.testing.assert_allclose(result["abs"], (b - 5).abs(), rtol=1e-12)
    np.testing.assert_allclose(result["round"], b.round(1), rtol=1e-12)
    np.testing.assert_allclose(result["floor"], np.floor(b), rtol=1e-12)
    np.testing.assert_allclose(result["ceil"], np.ceil(b), rtol=1e-12)
    np.testing.assert_allclose(result["sqrt"], np.sqrt(b), rtol=1e-12)
    np.testing.assert_allclose(result["sign"], np.sign(b - 5), rtol=1e-12)


def test_trigonometry(c, df):
    result = c.sql(
        """SELECT SIN(b) AS s, COS(b) AS co, TAN(b) AS t, ATAN(b) AS at
           FROM df""").to_pandas()
    b = df["b"]
    np.testing.assert_allclose(result["s"], np.sin(b), rtol=1e-12)
    np.testing.assert_allclose(result["co"], np.cos(b), rtol=1e-12)
    np.testing.assert_allclose(result["t"], np.tan(b), rtol=1e-9, atol=1e-9)
    np.testing.assert_allclose(result["at"], np.arctan(b), rtol=1e-12)


def test_integer_div(c, df_simple):
    result = c.sql("SELECT a / 2 AS d, a / -2 AS dn, 7 % a AS m FROM df_simple").to_pandas()
    # SQL integer division truncates toward zero
    assert list(result["d"]) == [0, 1, 1]
    assert list(result["dn"]) == [0, -1, -1]
    assert list(result["m"]) == [0, 1, 1]


def test_string_functions(c, string_table):
    result = c.sql(
        """SELECT
            a || 'hello' || a AS "a",
            CHAR_LENGTH(a) AS "c",
            UPPER(a) AS "u", LOWER(a) AS "l",
            SUBSTRING(a FROM 2 FOR 2) AS "s",
            POSITION('a' IN a) AS "p",
            TRIM('a' FROM a) AS "t",
            OVERLAY(a PLACING 'XXX' FROM 2) AS "o",
            INITCAP(a) AS "i",
            REPLACE(a, 'nor', 'NOR') AS "r"
        FROM string_table""").to_pandas()
    s = string_table["a"]
    assert list(result["a"]) == [x + "hello" + x for x in s]
    assert list(result["c"]) == [len(x) for x in s]
    assert list(result["u"]) == [x.upper() for x in s]
    assert list(result["l"]) == [x.lower() for x in s]
    assert list(result["s"]) == [x[1:3] for x in s]
    assert list(result["p"]) == [x.find("a") + 1 for x in s]
    assert list(result["t"]) == [x.strip("a") for x in s]
    assert list(result["o"]) == [x[:1] + "XXX" + x[4:] for x in s]
    assert list(result["r"]) == [x.replace("nor", "NOR") for x in s]


def test_like(c, string_table):
    assert len(c.sql(
        "SELECT * FROM string_table WHERE a LIKE '%n%'").to_pandas()) == 1
    assert len(c.sql(
        r"SELECT * FROM string_table WHERE a LIKE '\%\_\%' ESCAPE '\'").to_pandas()) == 1
    assert len(c.sql(
        "SELECT * FROM string_table WHERE a LIKE '%_%'").to_pandas()) == 3
    assert len(c.sql(
        "SELECT * FROM string_table WHERE a SIMILAR TO '.*string'").to_pandas()) == 1
    assert len(c.sql(
        "SELECT * FROM string_table WHERE a NOT LIKE '%n%'").to_pandas()) == 2


def test_coalesce_nullif(c):
    frame = pd.DataFrame({"a": [1.0, np.nan, 3.0], "b": [np.nan, 2.0, 4.0]})
    c.create_table("co", frame)
    result = c.sql(
        """SELECT COALESCE(a, b) AS c1, COALESCE(a, -1) AS c2,
                  NULLIF(a, 3) AS n1, GREATEST(a, b) AS g, LEAST(a, b) AS l
           FROM co""").to_pandas()
    assert list(result["c1"]) == [1.0, 2.0, 3.0]
    assert list(result["c2"]) == [1.0, -1.0, 3.0]
    assert result["n1"][0] == 1.0 and pd.isna(result["n1"][1]) and pd.isna(result["n1"][2])


def test_date_extract(c, datetime_table):
    result = c.sql(
        """SELECT EXTRACT(YEAR FROM no_timezone) AS y,
                  EXTRACT(MONTH FROM no_timezone) AS m,
                  EXTRACT(DAY FROM no_timezone) AS d,
                  EXTRACT(HOUR FROM no_timezone) AS h,
                  EXTRACT(MINUTE FROM no_timezone) AS mi,
                  EXTRACT(DOW FROM no_timezone) AS dow,
                  EXTRACT(DOY FROM no_timezone) AS doy,
                  EXTRACT(QUARTER FROM no_timezone) AS q
           FROM datetime_table""").to_pandas()
    dt = datetime_table["no_timezone"].dt
    assert list(result["y"]) == list(dt.year)
    assert list(result["m"]) == list(dt.month)
    assert list(result["d"]) == list(dt.day)
    assert list(result["h"]) == list(dt.hour)
    assert list(result["mi"]) == list(dt.minute)
    assert list(result["dow"]) == [(d + 1) % 7 for d in dt.dayofweek]
    assert list(result["doy"]) == list(dt.dayofyear)
    assert list(result["q"]) == list(dt.quarter)


def test_date_arithmetic(c, datetime_table):
    result = c.sql(
        """SELECT no_timezone + INTERVAL '1' DAY AS d1,
                  no_timezone - INTERVAL '2' HOUR AS d2,
                  FLOOR(no_timezone TO DAY) AS f,
                  CEIL(no_timezone TO DAY) AS ce
           FROM datetime_table""").to_pandas()
    dt = datetime_table["no_timezone"]
    assert list(result["d1"]) == list(dt + pd.Timedelta(days=1))
    assert list(result["d2"]) == list(dt - pd.Timedelta(hours=2))
    assert list(result["f"]) == list(dt.dt.floor("D"))
    assert list(result["ce"]) == list(dt.dt.ceil("D"))


def test_timestamp_minus(c, datetime_table):
    result = c.sql(
        """SELECT no_timezone - TIMESTAMP '2014-08-01 09:00' AS delta
           FROM datetime_table""").to_pandas()
    dt = datetime_table["no_timezone"]
    assert list(result["delta"]) == list(dt - pd.Timestamp("2014-08-01 09:00"))


def test_cast(c, df_simple):
    result = c.sql(
        """SELECT CAST(a AS DOUBLE) AS d, CAST(b AS INTEGER) AS i,
                  CAST(a AS VARCHAR) AS s, CAST('42' AS BIGINT) AS p,
                  CAST(a AS BOOLEAN) AS bo
           FROM df_simple""").to_pandas()
    assert list(result["d"]) == [1.0, 2.0, 3.0]
    assert list(result["i"]) == [1, 2, 3]  # truncation
    assert list(result["s"]) == ["1", "2", "3"]
    assert list(result["p"]) == [42, 42, 42]
    assert list(result["bo"]) == [True, True, True]


def test_is_distinct_from(c):
    frame = pd.DataFrame({"a": [1.0, np.nan, 3.0], "b": [1.0, np.nan, 4.0]})
    c.create_table("idf", frame)
    result = c.sql(
        """SELECT a IS DISTINCT FROM b AS d, a IS NOT DISTINCT FROM b AS nd
           FROM idf""").to_pandas()
    assert list(result["d"]) == [False, False, True]
    assert list(result["nd"]) == [True, True, False]


def test_in_list(c, df_simple):
    result = c.sql("SELECT a IN (1, 3) AS i FROM df_simple").to_pandas()
    assert list(result["i"]) == [True, False, True]


def test_rand(c, df_simple):
    result = c.sql("SELECT RAND(42) AS r, RAND_INTEGER(1, 10) AS ri FROM df_simple").to_pandas()
    assert ((result["r"] >= 0) & (result["r"] < 1)).all()
    assert ((result["ri"] >= 0) & (result["ri"] < 10)).all()


def test_between_symmetric(c, df_simple):
    result = c.sql(
        "SELECT a BETWEEN SYMMETRIC 3 AND 1 AS b FROM df_simple").to_pandas()
    assert list(result["b"]) == [True, True, True]
