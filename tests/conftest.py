"""Shared test fixtures.

Mirrors the reference's fixture catalog
(/root/reference/tests/integration/fixtures.py:25-173): the same 13 canonical
tables (nullable ints, inf, NaN, strings with regex metacharacters, tz-aware
datetimes) registered on a fresh Context, plus a sqlite differential-oracle
helper (the reference's eq_sqlite, test_compatibility.py:22-67).

Multi-device testing: an 8-device virtual CPU mesh via XLA host platform
flags, set before jax import (SURVEY §4 env-switch strategy).
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

# the image profile pins JAX_PLATFORMS=axon (the tunneled TPU); tests run on a
# virtual 8-device CPU mesh — config.update wins over the plugin registration
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the XLA_FLAGS
    # host-platform fallback set above (before the jax import) covers it
    pass

# The persistent compile cache is OPT-IN for tests (DSQL_TEST_CACHE=1).
# Two reasons, both observed as hard SIGSEGVs on other machines:
# - XLA:CPU AOT executables from another microarchitecture segfault on LOAD
#   ("machine features ... not supported" then SIGSEGV in
#   get_executable_and_time) — hence the per-CPU fingerprint in the dir name;
# - persisting EVERY executable (min_entry_size=-1/min_compile_time=0, as r2
#   shipped) segfaulted twice inside put_executable_and_time during
#   test_tpch_mesh at ~4.4 GB RSS with hundreds of cached SPMD executables.
# A cold suite only pays a few extra minutes of CPU compiles; a crashed suite
# proves nothing, so cold-by-default wins.
if os.environ.get("DSQL_TEST_CACHE") == "1":
    import hashlib as _hashlib

    try:
        with open("/proc/cpuinfo") as _f:
            _flags = "".join(sorted(l for l in _f if l.startswith("flags")))
        _cpu_fp = _hashlib.blake2b(_flags.encode(), digest_size=4).hexdigest()
    except OSError:
        _cpu_fp = "nocpuinfo"
    jax.config.update("jax_compilation_cache_dir",
                      f"/tmp/jax_test_cache_{_cpu_fp}")
    # default entry-size/compile-time thresholds: only big, slow compiles
    # are persisted, keeping the cache dir and write volume bounded

import numpy as np
import pandas as pd
import pytest

# The one-process 565-test suite segfaulted (r2 twice, r3 once) inside
# XLA:CPU's backend_compile_and_load while compiling test_tpch_mesh's big
# SPMD programs LATE in the run — with hundreds of live executables
# accumulated; the same file passes in isolation.  Two mitigations keep the
# single-process `pytest tests/` invocation (what CI and the driver run)
# healthy: (1) the heavy SPMD modules run FIRST while the process is fresh,
# (2) every module's compiled programs are dropped when the module ends, so
# live-executable count stays bounded at one module's worth.
_HEAVY_FIRST = ["test_tpch_mesh", "test_distributed", "test_tpch",
                "test_streaming"]


def pytest_collection_modifyitems(items):
    def rank(item):
        name = item.module.__name__.rsplit(".", 1)[-1]
        return (_HEAVY_FIRST.index(name) if name in _HEAVY_FIRST
                else len(_HEAVY_FIRST))
    items.sort(key=rank)


@pytest.fixture(autouse=True)
def _result_cache_off(request, monkeypatch):
    """The result cache (runtime/result_cache.py, on by default in
    production) would serve REPEATED queries from memory — which is exactly
    what the program-cache/resilience/telemetry suites repeat queries to
    observe (compile counters, retry ladders, stage spans).  Tests run with
    it off; the dedicated test_result_cache modules arm it explicitly, and
    scripts/cache_smoke.py gates the production-default path."""
    name = request.module.__name__
    # matview suites keep the cache: maintained aggregate state is a
    # result-cache tenant (runtime/matview.py) — with the cache off the
    # incremental path legitimately degrades to full recompute
    if "test_result_cache" not in name and "matview" not in name:
        monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "0")
    yield


@pytest.fixture(autouse=True)
def _scheduler_off(request, monkeypatch):
    """The workload manager (runtime/scheduler.py, on by default in
    production) adds admission waits and a ``queued`` span to every query —
    which would perturb the timing/span/counter assumptions of every
    pre-existing suite.  Mirroring the result-cache pin above: tests run
    with it off; the dedicated scheduler/workload suites arm it explicitly,
    and scripts/sched_smoke.py gates the production-default path."""
    name = request.module.__name__
    if "scheduler" not in name and "workload" not in name:
        monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "0")
    yield


@pytest.fixture(autouse=True)
def _quarantine_off(request, monkeypatch):
    """The cross-process quarantine store + compile watchdog
    (runtime/quarantine.py) are file/env-armed; an operator's environment
    must not leak verdicts into unrelated suites.  Mirroring the cache and
    scheduler pins: off by default, armed explicitly by the dedicated
    quarantine/failure-domain/drain suites."""
    name = request.module.__name__
    if ("quarantine" not in name and "failure" not in name
            and "drain" not in name and "chaos" not in name):
        monkeypatch.delenv("DSQL_QUARANTINE_FILE", raising=False)
        monkeypatch.delenv("DSQL_COMPILE_WATCHDOG_S", raising=False)
    yield


@pytest.fixture(autouse=True)
def _tiering_off(request, monkeypatch):
    """Tiered execution (physical/compiled.py, on by default in
    production) would answer every COLD query on the eager tier while the
    programs compile in the background — which would break every suite
    that asserts compiled-path usage or counts compiles synchronously.
    Mirroring the cache/scheduler/quarantine pins: off by default, armed
    explicitly by the dedicated tiered/program-store suites, and
    scripts/warmstart_smoke.py gates the production-default path."""
    name = request.module.__name__
    if "tiered" not in name and "program_store" not in name:
        monkeypatch.setenv("DSQL_TIERED", "0")
        monkeypatch.delenv("DSQL_PROGRAM_STORE", raising=False)
    yield


@pytest.fixture(autouse=True)
def _history_off(request, monkeypatch):
    """The flight recorder (runtime/flight_recorder.py) is file/env-armed
    like the quarantine store; an operator's DSQL_HISTORY_FILE must not
    make unrelated suites append to a real history ring (or perturb
    zero-overhead-path assumptions).  Off by default, armed explicitly by
    the dedicated flight-recorder/system-tables/engine suites, and
    scripts/obs_smoke.py gates the production path."""
    name = request.module.__name__
    if ("flight" not in name and "system_tables" not in name
            and "history" not in name and "engine" not in name):
        monkeypatch.delenv("DSQL_HISTORY_FILE", raising=False)
        monkeypatch.delenv("DSQL_HISTORY_MB", raising=False)
    yield


@pytest.fixture(autouse=True)
def _adaptive_off(request, monkeypatch):
    """Statistics-driven adaptive operator selection (runtime/statistics.py,
    on by default in production) changes which group-by/join kernel runs
    and how join chains are ordered — which would perturb every
    pre-existing suite's plan/counter/span assumptions.  Mirroring the
    cache/scheduler/tiering pins: non-adaptive suites run with the
    DSQL_ADAPTIVE=0 kill-switch pinned (plus any leaked DSQL_FORCE_GROUPBY
    cleared), the dedicated adaptive/statistics suites arm it explicitly,
    and scripts/stats_smoke.py gates the production-default path."""
    name = request.module.__name__
    if "adaptive" not in name and "statistic" not in name:
        monkeypatch.setenv("DSQL_ADAPTIVE", "0")
        monkeypatch.delenv("DSQL_FORCE_GROUPBY", raising=False)
    yield


@pytest.fixture(autouse=True)
def _profiler_off(request, monkeypatch):
    """The device profiler (runtime/profiler.py) is env-armed like the
    flight recorder; an operator's DSQL_PROFILE must not arm per-device
    sampling, forced AOT compiles and cost capture in unrelated suites
    (or break the zero-import tripwire test).  Off by default, armed
    explicitly by the dedicated profiler suites, and
    scripts/profile_smoke.py gates the production path."""
    if "profile" not in request.module.__name__:
        monkeypatch.delenv("DSQL_PROFILE", raising=False)
        monkeypatch.delenv("DSQL_PROFILE_SAMPLE_MS", raising=False)
    yield


@pytest.fixture(autouse=True)
def _events_off(request, monkeypatch):
    """The watchtower event bus + SLO monitor (runtime/events.py) is
    env-armed like the profiler; an operator's DSQL_EVENTS must not arm
    trace minting, event publication or SLO gauges in unrelated suites
    (or break the zero-import tripwire test).  Off by default, armed
    explicitly by the dedicated events suites, and
    scripts/events_smoke.py gates the production path."""
    if "event" not in request.module.__name__:
        monkeypatch.delenv("DSQL_EVENTS", raising=False)
        monkeypatch.delenv("DSQL_EVENTS_FILE", raising=False)
        monkeypatch.delenv("DSQL_TRACE_ID", raising=False)
    yield


@pytest.fixture(autouse=True)
def _autopilot_off(request, monkeypatch):
    """The autopilot (runtime/autopilot.py) is env-armed like the events
    bus; an operator's DSQL_AUTOPILOT must not arm matview creation or
    plan-hint rewrites in unrelated suites (or break the zero-import
    tripwire test), and DSQL_TENANT_WEIGHTS must not split the scheduler's
    fairness classes per tenant under pre-existing counter assertions.
    Off by default, armed explicitly by the dedicated autopilot suites,
    and scripts/autopilot_smoke.py gates the production path."""
    name = request.module.__name__
    if "autopilot" not in name:
        monkeypatch.delenv("DSQL_AUTOPILOT", raising=False)
        for _k in ("DSQL_AUTOPILOT_MV_MB", "DSQL_AUTOPILOT_SKEW",
                   "DSQL_AUTOPILOT_COST_ERR", "DSQL_AUTOPILOT_COLD_S",
                   "DSQL_AUTOPILOT_INTERVAL_S", "DSQL_AUTOPILOT_MIN_HITS",
                   "DSQL_AUTOPILOT_FILE"):
            monkeypatch.delenv(_k, raising=False)
    if "autopilot" not in name and "scheduler" not in name:
        monkeypatch.delenv("DSQL_TENANT_WEIGHTS", raising=False)
    yield


@pytest.fixture(autouse=True)
def _mesh_off(request, monkeypatch):
    """The SPMD multi-chip backend (parallel/spmd.py, on by default when a
    context carries a mesh) intercepts mesh-context queries before the
    compiled path — which would break every pre-existing mesh suite's
    compiled-stats/fallback assertions (test_tpch_mesh asserts the GSPMD
    whole-program path).  Mirroring the adaptive/history pins: non-SPMD
    suites run with the DSQL_MESH=0 kill-switch pinned, the dedicated
    spmd/shard suites arm it explicitly, and scripts/shard_smoke.py plus
    __graft_entry__.dryrun_multichip gate the production-default path."""
    name = request.module.__name__
    if "spmd" not in name and "shard" not in name:
        monkeypatch.setenv("DSQL_MESH", "0")
    yield


@pytest.fixture(autouse=True, scope="module")
def _bounded_executable_lifetime():
    yield
    from dask_sql_tpu.physical import compiled
    from dask_sql_tpu.runtime import faults, result_cache
    compiled._cache.clear()
    compiled._learned_caps.clear()
    compiled._runtime_eager.clear()
    with compiled._tier_lock:
        compiled._tier_done.clear()
        compiled._tier_inflight.clear()
    result_cache.get_cache().clear()
    faults.reset()
    jax.clear_caches()


@pytest.fixture()
def df_simple():
    return pd.DataFrame({"a": [1, 2, 3], "b": [1.1, 2.2, 3.3]})


@pytest.fixture()
def df():
    np.random.seed(42)
    return pd.DataFrame(
        {"a": [1.0] * 100 + [2.0] * 200 + [3.0] * 400, "b": 10 * np.random.rand(700)}
    )


@pytest.fixture()
def user_table_1():
    return pd.DataFrame({"user_id": [2, 1, 2, 3], "b": [3, 3, 1, 3]})


@pytest.fixture()
def user_table_2():
    return pd.DataFrame({"user_id": [1, 1, 2, 4], "c": [1, 2, 3, 4]})


@pytest.fixture()
def long_table():
    return pd.DataFrame({"a": [0] * 100 + [1] * 101 + [2] * 103})


@pytest.fixture()
def user_table_inf():
    return pd.DataFrame({"c": [3, float("inf"), 1]})


@pytest.fixture()
def user_table_nan():
    return pd.DataFrame({"c": pd.array([3, pd.NA, 1], dtype="UInt8")})


@pytest.fixture()
def string_table():
    return pd.DataFrame({"a": ["a normal string", "%_%", "^|()-*[]$"]})


@pytest.fixture()
def datetime_table():
    return pd.DataFrame(
        {
            "timezone": pd.date_range(
                start="2014-08-01 09:00", freq="h", periods=3, tz="Europe/Berlin"
            ),
            "no_timezone": pd.date_range(start="2014-08-01 09:00", freq="h", periods=3),
            "utc_timezone": pd.date_range(
                start="2014-08-01 09:00", freq="h", periods=3, tz="UTC"
            ),
        }
    )


@pytest.fixture()
def user_table_lk():
    out = pd.DataFrame(
        [[0, 5, 11, 111], [1, 2, pd.NA, 112], [1, 4, 13, 113], [3, 1, 14, 114]],
        columns=["id", "startdate", "lk_nullint", "lk_int"],
    )
    out["lk_nullint"] = out["lk_nullint"].astype("Int32")
    return out


@pytest.fixture()
def user_table_lk2():
    out = pd.DataFrame(
        [[2, pd.NA, 112], [4, 13, 113]], columns=["startdate", "lk_nullint", "lk_int"],
    )
    out["lk_nullint"] = out["lk_nullint"].astype("Int32")
    return out


@pytest.fixture()
def user_table_ts():
    out = pd.DataFrame([[1, 21], [3, pd.NA], [7, 23]], columns=["dates", "ts_nullint"])
    out["ts_nullint"] = out["ts_nullint"].astype("Int32")
    return out


@pytest.fixture()
def user_table_pn():
    out = pd.DataFrame(
        [[0, 1, pd.NA], [1, 5, 32], [2, 1, 33]], columns=["ids", "dates", "pn_nullint"],
    )
    out["pn_nullint"] = out["pn_nullint"].astype("Int32")
    return out


@pytest.fixture()
def c(df_simple, df, user_table_1, user_table_2, long_table, user_table_inf,
      user_table_nan, string_table, datetime_table, user_table_lk,
      user_table_lk2, user_table_ts, user_table_pn):
    dfs = {
        "df_simple": df_simple,
        "df": df,
        "user_table_1": user_table_1,
        "user_table_2": user_table_2,
        "long_table": long_table,
        "user_table_inf": user_table_inf,
        "user_table_nan": user_table_nan,
        "string_table": string_table,
        "datetime_table": datetime_table,
        "user_table_lk": user_table_lk,
        "user_table_lk2": user_table_lk2,
        "user_table_ts": user_table_ts,
        "user_table_pn": user_table_pn,
    }
    from dask_sql_tpu import Context

    ctx = Context()
    for df_name, frame in dfs.items():
        ctx.create_table(df_name, frame)
    yield ctx


# ---------------------------------------------------------------------------
# comparison helpers
# ---------------------------------------------------------------------------

def _normalize(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        s = out[col]
        if s.dtype == object:
            def conv(v):
                if v is None:
                    return None
                if isinstance(v, float) and np.isnan(v):
                    return None
                return v
            out[col] = s.map(conv)
        try:
            if s.dtype.kind in "iuf" or str(s.dtype) in (
                "Int8", "Int16", "Int32", "Int64", "UInt8", "UInt16", "UInt32",
                "UInt64", "Float32", "Float64"):
                out[col] = s.astype("float64")
            elif s.dtype.kind in "Mm":
                # pandas >= 2 keeps non-ns datetime64/timedelta64 resolutions
                # (the engine emits [us]); assert_frame_equal(check_dtype=
                # False) still compares the RAW int arrays, so unify units
                out[col] = s.astype(f"{s.dtype.name.split('[')[0]}[ns]")
        except (TypeError, AttributeError, OverflowError,
                pd.errors.OutOfBoundsDatetime):
            pass
    out.columns = [str(cname) for cname in out.columns]
    return out.reset_index(drop=True)


def assert_eq(result, expected, check_row_order: bool = True, **kwargs):
    """Frame comparison with dtype tolerance (int64 vs Int64 vs float64...)."""
    if hasattr(result, "to_pandas"):
        result = result.to_pandas()
    got = _normalize(result)
    exp = _normalize(expected)
    # an all-NULL aggregate lands as float64 NaN on one side and as an
    # object-dtype None on the other (pd.read_sql): both mean SQL NULL
    for col in got.columns:
        if col not in exp.columns:
            continue
        g, e = got[col], exp[col]
        if g.dtype == object and e.dtype.kind == "f" and g.isna().all():
            got[col] = g.astype("float64")
        elif e.dtype == object and g.dtype.kind == "f" and e.isna().all():
            exp[col] = e.astype("float64")
    if not check_row_order:
        got = got.sort_values(by=list(got.columns), na_position="last").reset_index(drop=True)
        exp = exp.sort_values(by=list(exp.columns), na_position="last").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False, rtol=1e-6, atol=1e-10)


@pytest.fixture()
def assert_query_eq(c):
    def _check(query: str, expected: pd.DataFrame, **kwargs):
        assert_eq(c.sql(query), expected, **kwargs)
    return _check


# ---------------------------------------------------------------------------
# sqlite differential oracle (reference test_compatibility.py:22-67)
# ---------------------------------------------------------------------------

def eq_sqlite(sql: str, check_row_order: bool = False, **dfs: pd.DataFrame):
    """Run the same SQL through dask_sql_tpu and in-memory sqlite, compare."""
    import sqlite3

    from dask_sql_tpu import Context

    ctx = Context()
    conn = sqlite3.connect(":memory:")
    for name, frame in dfs.items():
        ctx.create_table(name, frame)
        frame.to_sql(name, conn, index=False)

    got = ctx.sql(sql).to_pandas()
    expected = pd.read_sql(sql, conn)
    conn.close()

    assert_eq(got, expected, check_row_order=check_row_order)


def make_rand_df(size: int, **kwargs):
    """Random typed frame generator (reference fugue-derived helper,
    test_compatibility.py:34-67 uses the same idea)."""
    np.random.seed(0)
    data = {}
    for name, spec in kwargs.items():
        nulls = None
        if isinstance(spec, tuple):
            dtype, null_ct = spec
        else:
            dtype, null_ct = spec, 0
        if dtype is int:
            arr = np.random.randint(0, 10, size).astype("float64" if null_ct else "int64")
        elif dtype is bool:
            arr = np.random.randint(0, 2, size).astype(bool)
            if null_ct:
                arr = pd.array(arr, dtype="boolean")
        elif dtype is float:
            arr = np.round(np.random.rand(size) * 10, 3)
        elif dtype is str:
            arr = np.random.choice([f"s{i}" for i in range(6)], size).astype(object)
        elif dtype == "datetime":
            arr = pd.to_datetime(np.random.randint(1577836800, 1609459200, size), unit="s")
        else:
            raise ValueError(dtype)
        s = pd.Series(arr)
        if null_ct:
            idx = np.random.choice(size, null_ct, replace=False)
            if dtype is str:
                s = s.astype(object)
                s.iloc[idx] = None
            elif dtype is int:
                s.iloc[idx] = np.nan
            elif dtype is bool:
                s.iloc[idx] = pd.NA
            else:
                s.iloc[idx] = np.nan
        data[name] = s
    return pd.DataFrame(data)


needs_compiled = pytest.mark.skipif(
    os.environ.get("DSQL_COMPILE") == "0",
    reason="asserts compiled-path usage; meaningless with DSQL_COMPILE=0")
