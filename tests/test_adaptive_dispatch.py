"""Integration tests for statistics-driven adaptive operator selection.

Pandas-oracle parity across ALL forced group-by variants on TPC-H-shaped
queries, dense direct-index proof (counter + EXPLAIN line), high-NDV
fallback to hash, the DSQL_ADAPTIVE=0 kill switch, and the
system.table_stats / QueryReport surfaces.

The module name contains "adaptive", so conftest's _adaptive_off pin
leaves production defaults alone here; each test sets exactly the env it
asserts.  DSQL_COMPILE=0 where a test asserts EAGER dispatch counters —
the compiled path fuses the whole plan and never reaches the eager
group_codes dispatch.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import telemetry as _tel

from tests.conftest import assert_eq

VARIANTS = ("hash", "sorted", "dense")


def _counters():
    return dict(_tel.REGISTRY.counters())


def _delta(before, key):
    return _tel.REGISTRY.counters().get(key, 0) - before.get(key, 0)


@pytest.fixture()
def tpch_ctx():
    """A small TPC-H-shaped catalog: lineitem fact + part/orders dims."""
    np.random.seed(7)
    n = 6000
    lineitem = pd.DataFrame({
        "l_orderkey": np.random.randint(0, 1500, n),
        "l_partkey": np.random.randint(0, 200, n),
        "l_quantity": np.random.randint(1, 51, n).astype("float64"),
        "l_extendedprice": np.round(np.random.rand(n) * 1e4, 2),
        "l_discount": np.round(np.random.rand(n) * 0.1, 2),
        "l_returnflag": np.random.choice(["A", "N", "R"], n),
        "l_linestatus": np.random.choice(["O", "F"], n),
    })
    orders = pd.DataFrame({
        "o_orderkey": np.arange(1500),
        "o_custkey": np.random.randint(0, 150, 1500),
        "o_totalprice": np.round(np.random.rand(1500) * 1e5, 2),
    })
    part = pd.DataFrame({
        "p_partkey": np.arange(200),
        "p_size": np.random.randint(1, 50, 200),
    })
    ctx = Context()
    ctx.create_table("lineitem", lineitem)
    ctx.create_table("orders", orders)
    ctx.create_table("part", part)
    return ctx, {"lineitem": lineitem, "orders": orders, "part": part}


Q1_SHAPED = (
    "SELECT l_returnflag, l_linestatus, SUM(l_quantity) AS sum_qty, "
    "SUM(l_extendedprice * (1 - l_discount)) AS sum_disc, "
    "AVG(l_quantity) AS avg_qty, COUNT(*) AS count_order "
    "FROM lineitem GROUP BY l_returnflag, l_linestatus"
)

DENSE_KEY_AGG = (
    "SELECT l_partkey, SUM(l_quantity) AS s, COUNT(*) AS n, "
    "MIN(l_extendedprice) AS mn, MAX(l_extendedprice) AS mx "
    "FROM lineitem GROUP BY l_partkey"
)

JOIN_AGG = (
    "SELECT o_custkey, SUM(l_extendedprice) AS rev "
    "FROM lineitem, orders WHERE l_orderkey = o_orderkey "
    "GROUP BY o_custkey"
)


def _oracle_q1(frames):
    li = frames["lineitem"].copy()
    li["disc"] = li["l_extendedprice"] * (1 - li["l_discount"])
    return (li.groupby(["l_returnflag", "l_linestatus"])
            .agg(sum_qty=("l_quantity", "sum"), sum_disc=("disc", "sum"),
                 avg_qty=("l_quantity", "mean"),
                 count_order=("l_quantity", "size")).reset_index())


def _oracle_dense(frames):
    return (frames["lineitem"].groupby("l_partkey")
            .agg(s=("l_quantity", "sum"), n=("l_quantity", "size"),
                 mn=("l_extendedprice", "min"),
                 mx=("l_extendedprice", "max")).reset_index())


def _oracle_join_agg(frames):
    j = frames["lineitem"].merge(frames["orders"],
                                 left_on="l_orderkey", right_on="o_orderkey")
    return (j.groupby("o_custkey")
            .agg(rev=("l_extendedprice", "sum")).reset_index())


@pytest.mark.parametrize("variant", VARIANTS)
@pytest.mark.parametrize("sql,oracle", [
    (Q1_SHAPED, _oracle_q1),
    (DENSE_KEY_AGG, _oracle_dense),
    (JOIN_AGG, _oracle_join_agg),
], ids=["q1-shaped", "dense-key", "join-agg"])
def test_forced_variant_pandas_parity(tpch_ctx, monkeypatch, sql, oracle,
                                      variant):
    """Every forced variant must agree with the pandas oracle — the
    group-numbering parity invariant, end to end.  (A variant that does
    not apply — dense over string keys — falls through and must STILL
    agree.)"""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    monkeypatch.setenv("DSQL_FORCE_GROUPBY", variant)
    ctx, frames = tpch_ctx
    assert_eq(ctx.sql(sql), oracle(frames), check_row_order=False)


def test_dense_key_takes_direct_index_path(tpch_ctx, monkeypatch):
    """Acceptance: a dense small-domain key PROVABLY takes the dense
    direct-index path — counter + EXPLAIN line, not just equal output."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    ctx, frames = tpch_ctx
    before = _counters()
    assert_eq(ctx.sql(DENSE_KEY_AGG), _oracle_dense(frames),
              check_row_order=False)
    assert _delta(before, "operator_choice_groupby_dense") >= 1
    text = ctx.sql("EXPLAIN " + DENSE_KEY_AGG) \
              .to_pandas()["PLAN"].str.cat(sep="\n")
    assert "-- operator: groupby=dense" in text
    assert "ndv=" in text and "rows=" in text


def test_high_ndv_takes_hash(monkeypatch):
    """Acceptance: a high-NDV key (near-unique, wide domain) stays on
    hash aggregation."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    n = 50_000
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({
        "k": np.arange(n, dtype=np.int64) * 1001,  # wide domain, ndv = n
        "v": np.random.rand(n)}))
    before = _counters()
    ctx.sql("SELECT k, SUM(v) FROM t GROUP BY k")
    assert _delta(before, "operator_choice_groupby_hash") >= 1
    assert _delta(before, "operator_choice_groupby_dense") == 0
    assert _delta(before, "operator_choice_groupby_sorted") == 0


def test_sorted_crossover_fat_groups(monkeypatch):
    """Low NDV over a wide (non-dense) domain with fat groups crosses to
    sorted-segment aggregation."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    n = 40_000
    keys = (np.arange(n, dtype=np.int64) % 20) * 10**7  # ndv=20, wide
    ctx = Context()
    df = pd.DataFrame({"k": keys, "v": np.random.rand(n)})
    ctx.create_table("t", df)
    before = _counters()
    got = ctx.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k")
    assert _delta(before, "operator_choice_groupby_sorted") >= 1
    assert_eq(got, df.groupby("k").agg(s=("v", "sum")).reset_index(),
              check_row_order=False)


def test_dense_join_direct_index(monkeypatch):
    """Small/dense single-int join keys take the dense join coding, with
    the choice recorded."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    np.random.seed(3)
    left = pd.DataFrame({"k": np.random.randint(0, 64, 4000),
                         "a": np.random.rand(4000)})
    right = pd.DataFrame({"k": np.arange(64), "b": np.random.rand(64)})
    ctx = Context()
    ctx.create_table("l", left)
    ctx.create_table("r", right)
    before = _counters()
    got = ctx.sql("SELECT l.k, a, b FROM l, r WHERE l.k = r.k")
    assert _delta(before, "operator_choice_join_dense") >= 1
    exp = left.merge(right, on="k")[["k", "a", "b"]]
    assert_eq(got, exp, check_row_order=False)


def test_adaptive_off_restores_baseline(tpch_ctx, monkeypatch):
    """DSQL_ADAPTIVE=0: no adaptive counters move, no EXPLAIN trailer,
    and results match the oracle (status-quo hash dispatch)."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    monkeypatch.delenv("DSQL_FORCE_GROUPBY", raising=False)
    ctx, frames = tpch_ctx
    before = _counters()
    assert_eq(ctx.sql(DENSE_KEY_AGG), _oracle_dense(frames),
              check_row_order=False)
    assert_eq(ctx.sql(JOIN_AGG), _oracle_join_agg(frames),
              check_row_order=False)
    for key in ("operator_choice_groupby_dense",
                "operator_choice_groupby_sorted",
                "operator_choice_join_dense",
                "operator_choice_join_order_stats"):
        assert _delta(before, key) == 0, key
    text = ctx.sql("EXPLAIN " + DENSE_KEY_AGG) \
              .to_pandas()["PLAN"].str.cat(sep="\n")
    assert "-- operator:" not in text


def test_forced_beats_kill_switch_precedence(tpch_ctx, monkeypatch):
    """DSQL_FORCE_GROUPBY works even with DSQL_ADAPTIVE=0 (explicit
    operator pinning is an operator decision, not an adaptive one)."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    monkeypatch.setenv("DSQL_FORCE_GROUPBY", "dense")
    ctx, frames = tpch_ctx
    before = _counters()
    assert_eq(ctx.sql(DENSE_KEY_AGG), _oracle_dense(frames),
              check_row_order=False)
    assert _delta(before, "operator_choice_groupby_dense") >= 1


def test_system_table_stats_queryable(tpch_ctx):
    ctx, frames = tpch_ctx
    df = ctx.sql(
        'SELECT "table", "column", ndv, dense, "rows" '
        "FROM system.table_stats WHERE \"table\" = 'lineitem'"
    ).to_pandas()
    row = df[df["column"] == "l_partkey"].iloc[0]
    assert bool(row["dense"])
    assert int(row["ndv"]) == frames["lineitem"]["l_partkey"].nunique()
    assert int(row["rows"]) == len(frames["lineitem"])


def test_query_report_carries_operators(tpch_ctx, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE", "0")
    ctx, _ = tpch_ctx
    ctx.sql(DENSE_KEY_AGG)
    rep = _tel.last_report()
    assert rep is not None
    assert any(op.startswith("groupby=dense") for op in rep.operators)
    assert rep.to_dict()["operators"] == rep.operators


def test_explain_analyze_prints_measured_choices(tpch_ctx, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE", "0")
    ctx, _ = tpch_ctx
    text = ctx.sql("EXPLAIN ANALYZE " + DENSE_KEY_AGG) \
              .to_pandas()["PLAN"].str.cat(sep="\n")
    assert "-- operator: groupby=dense" in text


def test_compiled_parity_with_cap_hints(tpch_ctx):
    """The compiled path with stats cap hints agrees with the oracle —
    a too-small hint must escalate, never corrupt."""
    ctx, frames = tpch_ctx
    assert_eq(ctx.sql(DENSE_KEY_AGG), _oracle_dense(frames),
              check_row_order=False)
    assert_eq(ctx.sql(Q1_SHAPED), _oracle_q1(frames),
              check_row_order=False)


def test_null_keys_parity_all_variants(monkeypatch):
    """NULL group keys keep parity on every variant (NULL-first
    numbering is part of the shared invariant)."""
    monkeypatch.setenv("DSQL_COMPILE", "0")
    df = pd.DataFrame({"k": pd.array([2, None, 1, 2, None, 1, 3], "Int64"),
                       "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]})
    exp = (df.groupby("k", dropna=False).agg(s=("v", "sum"))
           .reset_index())
    for variant in VARIANTS:
        monkeypatch.setenv("DSQL_FORCE_GROUPBY", variant)
        ctx = Context()
        ctx.create_table("t", df)
        assert_eq(ctx.sql("SELECT k, SUM(v) AS s FROM t GROUP BY k"),
                  exp, check_row_order=False)
