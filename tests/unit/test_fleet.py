"""Unit tests for the fleet plane (runtime/fleet.py): replica identity,
heartbeat TTL/corruption tolerance, merged rings with composite cursors,
tenant-gauge cardinality, and the zero-import disabled path."""
import json
import os
import subprocess
import sys
import time

import pytest


@pytest.fixture()
def fleet(tmp_path, monkeypatch):
    """Armed fleet module in a private dir; tears down the heartbeater
    and the env defaults ensure_armed installs (setdefault writes are
    invisible to monkeypatch, so clear them explicitly)."""
    monkeypatch.setenv("DSQL_FLEET_DIR", str(tmp_path))
    monkeypatch.setenv("DSQL_FLEET_BEAT_S", "0.1")
    monkeypatch.setenv("DSQL_REPLICA_ID", "r-a")
    for key in ("DSQL_EVENTS", "DSQL_EVENTS_FILE", "DSQL_HISTORY_FILE"):
        monkeypatch.delenv(key, raising=False)
    from dask_sql_tpu.runtime import events
    from dask_sql_tpu.runtime import fleet as fl
    fl._reset_for_tests()
    events._reset_for_tests()
    yield fl
    fl._reset_for_tests()
    events._reset_for_tests()
    for key in ("DSQL_EVENTS", "DSQL_EVENTS_FILE", "DSQL_HISTORY_FILE"):
        os.environ.pop(key, None)


# ---------------------------------------------------------------------------
# identity + arming
# ---------------------------------------------------------------------------

def test_replica_id_sanitized(fleet, monkeypatch):
    monkeypatch.setenv("DSQL_REPLICA_ID", "ok-Name_1.x")
    fleet._reset_for_tests()
    assert fleet.replica_id() == "ok-Name_1.x"
    monkeypatch.setenv("DSQL_REPLICA_ID", "bad id/../../etc")
    fleet._reset_for_tests()
    rid = fleet.replica_id()
    assert "/" not in rid and " " not in rid
    monkeypatch.delenv("DSQL_REPLICA_ID")
    fleet._reset_for_tests()
    assert fleet.replica_id().endswith(f"-{os.getpid()}")


def test_ensure_armed_installs_ring_redirection(fleet):
    assert fleet.ensure_armed() is True
    assert os.environ["DSQL_EVENTS"] == "1"
    assert os.environ["DSQL_EVENTS_FILE"] == fleet.events_path("r-a")
    assert os.environ["DSQL_HISTORY_FILE"] == fleet.history_path("r-a")
    # idempotent, and explicit user values win over the defaults
    assert fleet.ensure_armed() is True


def test_ensure_armed_noop_when_unset(fleet, monkeypatch):
    monkeypatch.delenv("DSQL_FLEET_DIR")
    fleet._reset_for_tests()
    assert fleet.ensure_armed() is False
    assert "DSQL_EVENTS" not in os.environ


# ---------------------------------------------------------------------------
# heartbeats: TTL expiry and corruption tolerance
# ---------------------------------------------------------------------------

def test_heartbeat_ttl_expiry_of_dead_replica(fleet):
    fleet.ensure_armed()
    # a "killed" replica: its heartbeat file exists but the beat is
    # older than the TTL — must be listed but not alive
    stale = {"replica": "r-dead", "pid": 99999, "host": "gone",
             "started": time.time() - 100,
             "beat": time.time() - 100}
    with open(fleet.heartbeat_path("r-dead"), "w") as f:
        json.dump(stale, f)
    reps = {r["replica"]: r for r in fleet.read_replicas()}
    assert reps["r-a"]["alive"] is True
    assert reps["r-dead"]["alive"] is False
    assert reps["r-dead"]["age_s"] > fleet.ttl_s()
    # snapshot totals only sum the alive replicas
    snap = fleet.snapshot()
    assert snap["totals"]["replicas"] == 2
    assert snap["totals"]["alive"] == 1


def test_corrupt_and_torn_heartbeats_skipped(fleet):
    fleet.ensure_armed()
    rd = fleet.replicas_dir()
    with open(os.path.join(rd, "torn.json"), "w") as f:
        f.write('{"replica": "r-torn", "pid"')       # torn mid-write
    with open(os.path.join(rd, "scalar.json"), "w") as f:
        f.write("42")                                # valid JSON, not a dict
    with open(os.path.join(rd, "empty.json"), "w") as f:
        pass
    with open(os.path.join(rd, "anon.json"), "w") as f:
        json.dump({"pid": 1}, f)                     # dict, no identity
    reps = fleet.read_replicas()
    assert [r["replica"] for r in reps] == ["r-a"]


def test_heartbeat_payload_shape(fleet):
    fleet.ensure_armed()
    hb = fleet.collect_heartbeat()
    for key in ("replica", "pid", "host", "started", "beat",
                "counters", "scheduler", "memory", "programStore"):
        assert key in hb, key
    assert hb["replica"] == "r-a"
    assert hb["pid"] == os.getpid()


# ---------------------------------------------------------------------------
# merged rings + composite cursor
# ---------------------------------------------------------------------------

def _write_ring(fleet, rid, recs):
    with open(fleet.events_path(rid), "a") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")


def test_merged_events_timestamp_order(fleet):
    fleet.ensure_armed()
    base = time.time()
    _write_ring(fleet, "r-b", [
        {"seq": 1, "unix": base + 0.2, "pid": 2, "trace": "t1",
         "type": "b.first"},
        {"seq": 2, "unix": base + 0.4, "pid": 2, "trace": "t1",
         "type": "b.second"},
    ])
    _write_ring(fleet, "r-c", [
        {"seq": 1, "unix": base + 0.1, "pid": 3, "trace": "t1",
         "type": "c.first"},
        {"seq": 2, "unix": base + 0.3, "pid": 3, "trace": "t2",
         "type": "c.second"},
    ])
    rows = fleet.merged_events_rows()
    assert [r["type"] for r in rows] == [
        "c.first", "b.first", "c.second", "b.second"]
    assert [r["replica"] for r in rows] == ["r-c", "r-b", "r-c", "r-b"]
    # one trace id stitches across replicas
    t1 = [r for r in rows if r["trace"] == "t1"]
    assert {r["replica"] for r in t1} == {"r-b", "r-c"}


def test_composite_cursor_monotonic_and_lossless(fleet):
    fleet.ensure_armed()
    base = time.time()
    _write_ring(fleet, "r-b", [
        {"seq": i, "unix": base + i * 0.1, "pid": 2, "type": f"b.{i}"}
        for i in range(1, 6)])
    _write_ring(fleet, "r-c", [
        {"seq": i, "unix": base + i * 0.1 + 0.05, "pid": 3,
         "type": f"c.{i}"} for i in range(1, 6)])
    seen, cursor = [], ""
    for _ in range(20):
        batch, nxt = fleet.read_merged_since(cursor, limit=3)
        if not batch:
            assert nxt == cursor        # cursor never regresses when idle
            break
        seen.extend(batch)
        cursor = nxt
    assert [r["type"] for r in seen if r["replica"] == "r-b"] == \
        [f"b.{i}" for i in range(1, 6)]
    assert [r["type"] for r in seen if r["replica"] == "r-c"] == \
        [f"c.{i}" for i in range(1, 6)]
    assert len(seen) == 10              # lossless: every event exactly once
    # globally timestamp-ordered
    assert [r["unix"] for r in seen] == sorted(r["unix"] for r in seen)


def test_cursor_roundtrip_tolerant(fleet):
    assert fleet.parse_cursor(None) == {}
    assert fleet.parse_cursor("") == {}
    assert fleet.parse_cursor("garbage") == {}
    assert fleet.parse_cursor("r-a:zzz;r-b:3") == {"r-b": 3}
    cur = {"r-a": 7, "r-b": 3}
    assert fleet.parse_cursor(fleet.encode_cursor(cur)) == cur


def test_merged_query_rows_stamp_replica(fleet):
    fleet.ensure_armed()
    with open(fleet.history_path("r-b"), "a") as f:
        f.write(json.dumps({"kind": "query", "unix": time.time(),
                            "sql": "SELECT 1", "wall_ms": 3.0}) + "\n")
        f.write(json.dumps({"kind": "stage", "unix": time.time()}) + "\n")
    rows = fleet.merged_query_rows()
    assert len(rows) == 1 and rows[0]["replica"] == "r-b"


# ---------------------------------------------------------------------------
# tenant-gauge cardinality bound
# ---------------------------------------------------------------------------

def test_tenant_gauge_cardinality_bounded(monkeypatch):
    monkeypatch.setenv("DSQL_MAX_TENANT_GAUGES", "3")
    from dask_sql_tpu.runtime import events, telemetry
    events._reset_for_tests()
    for i in range(8):
        events.observe_tenant(f"tenant-{i}", "interactive", 1.0)
    gauges = {k: v for k, v in telemetry.REGISTRY.snapshot()["gauges"].items()
              if k.startswith("slo_attainment_tenant_")}
    named = [k for k in gauges if not k.endswith("_other")]
    assert len(named) == 3
    assert "slo_attainment_tenant__other" in gauges
    # existing tenants keep their own series even after overflow
    events.observe_tenant("tenant-0", "interactive", 1.0)
    snap = telemetry.REGISTRY.snapshot()["gauges"]
    assert "slo_attainment_tenant_tenant-0" in snap
    events._reset_for_tests()


# ---------------------------------------------------------------------------
# the zero-import disabled path
# ---------------------------------------------------------------------------

def test_disabled_query_never_imports_fleet():
    """With DSQL_FLEET_DIR unset an end-to-end query must leave
    runtime.fleet out of sys.modules — the fleet plane costs one env
    read when off."""
    code = (
        "import sys\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3]})\n"
        "assert c.sql('SELECT SUM(a) AS s FROM t').to_pylist() == [[6]]\n"
        "assert 'dask_sql_tpu.runtime.fleet' not in sys.modules, \\\n"
        "    'disabled path imported the fleet plane'\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()


def test_system_replicas_empty_when_disarmed(monkeypatch):
    monkeypatch.delenv("DSQL_FLEET_DIR", raising=False)
    from dask_sql_tpu.runtime import system_tables as st
    t = st.build("replicas")
    assert t.num_rows == 0
    assert "replica" in t.names
