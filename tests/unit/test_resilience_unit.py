"""Resilience primitives: taxonomy classification, deadlines/cancellation,
retry/backoff policy, and the fault-injection spec machinery
(runtime/resilience.py + runtime/faults.py)."""
import threading
import time

import pytest

from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import faults, resilience as R


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.reset()
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "1")
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# taxonomy / classify
# ---------------------------------------------------------------------------

def test_classify_passthrough_typed_and_control_flow():
    err = R.TransientError("x", kind="io")
    assert R.classify(err) is err
    assert R.classify(KeyboardInterrupt()) is None
    assert R.classify(SystemExit()) is None


def test_classify_xla_statuses():
    class XlaRuntimeError(Exception):
        pass

    oom = R.classify(XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert isinstance(oom, R.TransientError) and oom.kind == "oom"
    assert oom.error_type == "INSUFFICIENT_RESOURCES"
    fatal = R.classify(XlaRuntimeError("INVALID_ARGUMENT: bad hlo"))
    assert isinstance(fatal, R.FatalError)
    transient = R.classify(XlaRuntimeError("INTERNAL: socket closed"))
    assert isinstance(transient, R.TransientError)


def test_classify_user_and_defaults():
    class ValidationException(Exception):
        pass

    assert isinstance(R.classify(ValidationException("no such column")),
                      R.UserError)
    assert isinstance(R.classify(MemoryError()), R.TransientError)
    assert isinstance(R.classify(ConnectionError()), R.TransientError)
    assert isinstance(R.classify(TypeError("boom")), R.FatalError)
    assert isinstance(R.classify(TypeError("boom"), default=R.UserError),
                      R.UserError)
    # original rides along for tracebacks
    src = ValueError("source")
    assert R.classify(src).__cause__ is src


def test_taxonomy_wire_attributes():
    assert R.UserError("x").error_type == "USER_ERROR"
    assert R.FatalError("x").error_type == "INTERNAL_ERROR"
    assert R.TransientError("x").error_type == "INTERNAL_ERROR"
    assert R.DeadlineExceeded("x").error_type == "INSUFFICIENT_RESOURCES"
    assert R.DeadlineExceeded("x").error_name == "EXCEEDED_TIME_LIMIT"
    assert isinstance(R.QueryCancelled("x"), R.UserError)
    assert R.QueryCancelled("x").error_name == "USER_CANCELED"
    # the streaming executor's typed refusal is a UserError AND still a
    # RuntimeError for pre-taxonomy callers
    from dask_sql_tpu.physical.streaming import StreamingUnsupported
    assert issubclass(StreamingUnsupported, R.UserError)
    assert issubclass(StreamingUnsupported, RuntimeError)
    from dask_sql_tpu.io.chunked import ChunkedInputError
    assert issubclass(ChunkedInputError, R.UserError)
    assert issubclass(ChunkedInputError, ValueError)


# ---------------------------------------------------------------------------
# deadlines + cancellation
# ---------------------------------------------------------------------------

def test_check_is_noop_outside_scope():
    R.check("anywhere")  # no scope, no deadline: must not raise


def test_deadline_expires():
    with R.query_scope(timeout_s=0.0):
        with pytest.raises(R.DeadlineExceeded):
            R.check("site")


def test_nested_scope_keeps_sooner_deadline():
    with R.query_scope(timeout_s=0.0):
        with R.query_scope(timeout_s=100.0):
            with pytest.raises(R.DeadlineExceeded):
                R.check()


def test_env_default_timeout(monkeypatch):
    monkeypatch.setenv("DSQL_QUERY_TIMEOUT_MS", "1")
    with R.query_scope():
        time.sleep(0.01)
        with pytest.raises(R.DeadlineExceeded):
            R.check()


def test_cancel_token_reaches_nested_scope():
    cancel = threading.Event()
    with R.query_scope(cancel=cancel):
        with R.query_scope(timeout_s=100.0):
            R.check()
            cancel.set()
            with pytest.raises(R.QueryCancelled):
                R.check()


def test_scoped_reenters_runtime_in_worker_thread():
    cancel = threading.Event()
    cancel.set()
    seen = []
    with R.query_scope(cancel=cancel) as rt:
        def worker():
            with R.scoped(rt):
                try:
                    R.check("worker")
                except R.QueryCancelled:
                    seen.append(True)
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen == [True]


def test_interruptible_sleep_cut_by_deadline():
    t0 = time.monotonic()
    with R.query_scope(timeout_s=0.05):
        with pytest.raises(R.DeadlineExceeded):
            R.interruptible_sleep(30.0, "test")
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

def test_retry_transient_succeeds_after_blip():
    before = compiled.stats["retries"]
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise R.TransientError("blip", kind="io")
        return "ok"

    assert R.retry_transient(flaky, site="t") == "ok"
    assert len(calls) == 2
    assert compiled.stats["retries"] == before + 1


def test_retry_transient_exhausts_typed(monkeypatch):
    monkeypatch.setenv("DSQL_RETRY_MAX", "1")

    def always():
        raise OSError("tunnel down")   # classifies transient

    with pytest.raises(R.TransientError):
        R.retry_transient(always, site="t")


def test_retry_transient_fatal_is_immediate():
    calls = []

    def fatal():
        calls.append(1)
        raise TypeError("trace bug")

    with pytest.raises(R.FatalError):
        R.retry_transient(fatal, site="t")
    assert len(calls) == 1


def test_retry_transient_passthrough():
    class Control(Exception):
        pass

    def ctl():
        raise Control()

    with pytest.raises(Control):
        R.retry_transient(ctl, site="t", passthrough=(Control,))


def test_backoff_respects_deadline():
    with R.query_scope(timeout_s=0.001):
        with pytest.raises(R.DeadlineExceeded):
            # backoff for a late attempt needs more budget than 1 ms
            R.backoff(8, "t")


# ---------------------------------------------------------------------------
# fault injection machinery
# ---------------------------------------------------------------------------

def test_parse_spec_shapes():
    specs = faults.parse_spec("compile:1,stage_exec:3+,materialize:2:sleep=50")
    assert [(s.site, s.nth, s.from_on, s.sleep_ms) for s in specs] == [
        ("compile", 1, False, None), ("stage_exec", 3, True, None),
        ("materialize", 2, False, 50)]
    with pytest.raises(ValueError):
        faults.parse_spec("nosuchsite:1")
    with pytest.raises(ValueError):
        faults.parse_spec("compile")
    with pytest.raises(ValueError):
        faults.parse_spec("compile:1:frob=2")


def test_maybe_fail_nth_semantics():
    before = compiled.stats["fault_compile"]
    with faults.inject("compile:2"):
        faults.maybe_fail("compile")          # 1st: no fire
        faults.maybe_fail("materialize")      # other site: own counter
        with pytest.raises(faults.FaultInjected) as ei:
            faults.maybe_fail("compile")      # 2nd: fires
        assert ei.value.site == "compile"
        assert isinstance(ei.value, R.TransientError)
        faults.maybe_fail("compile")          # 3rd: no fire (nth, not nth+)
    assert compiled.stats["fault_compile"] == before + 1
    faults.maybe_fail("compile")              # disarmed outside the cm


def test_maybe_fail_from_on_semantics():
    with faults.inject("compile:2+"):
        faults.maybe_fail("compile")
        for _ in range(3):
            with pytest.raises(faults.FaultInjected):
                faults.maybe_fail("compile")


def test_env_spec_is_read_per_call(monkeypatch):
    monkeypatch.setenv("DSQL_FAULT_INJECT", "materialize:1")
    faults.reset()
    with pytest.raises(faults.FaultInjected):
        faults.maybe_fail("materialize")
    monkeypatch.delenv("DSQL_FAULT_INJECT")
    faults.maybe_fail("materialize")


# ---------------------------------------------------------------------------
# probabilistic arming + fatal action (the chaos-soak spec forms)
# ---------------------------------------------------------------------------

def test_parse_probabilistic_and_fatal_spec():
    specs = faults.parse_spec(
        "compile:p=0.25:seed=7,stage_replay:1,drain:1,compile:2:fatal")
    assert specs[0].prob == 0.25 and specs[0].rng is not None
    assert specs[0].nth is None
    assert [s.site for s in specs[1:3]] == ["stage_replay", "drain"]
    assert specs[3].fatal
    with pytest.raises(ValueError):
        faults.parse_spec("compile:p=0")          # outside (0, 1]
    with pytest.raises(ValueError):
        faults.parse_spec("compile:p=1.5")


def test_probabilistic_fire_rate_is_seeded_and_deterministic():
    def fires(spec):
        out = []
        with faults.inject(spec):
            for i in range(200):
                try:
                    faults.maybe_fail("compile")
                    out.append(False)
                except faults.FaultInjected:
                    out.append(True)
        return out

    a = fires("compile:p=0.2:seed=11")
    b = fires("compile:p=0.2:seed=11")
    assert a == b, "same seed must reproduce the same fault sequence"
    rate = sum(a) / len(a)
    assert 0.05 < rate < 0.45, f"p=0.2 spec fired at {rate}"
    c = fires("compile:p=0.2:seed=12")
    assert a != c, "different seeds should diverge"


def test_fatal_action_raises_fatal_typed():
    before = compiled.stats["fault_compile"]
    with faults.inject("compile:1:fatal"):
        with pytest.raises(faults.FatalFaultInjected) as ei:
            faults.maybe_fail("compile")
    assert isinstance(ei.value, R.FatalError)
    assert not isinstance(ei.value, R.TransientError)
    assert compiled.stats["fault_compile"] == before + 1


def test_new_sites_registered():
    for site in ("stage_replay", "drain"):
        assert site in faults.SITES


# ---------------------------------------------------------------------------
# retry-backoff accounting (feeds the scheduler's honest hold-time EWMA)
# ---------------------------------------------------------------------------

def test_backoff_accrues_on_runtime(monkeypatch):
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "30")
    with R.query_scope() as rt:
        assert rt.backoff_s == 0.0
        R.backoff(1, "t")
        assert rt.backoff_s >= 0.025
        R.backoff(1, "t")
        assert rt.backoff_s >= 0.05


def test_backoff_accrual_survives_deadline_cut(monkeypatch):
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "400")
    with R.query_scope(timeout_s=0.05) as rt:
        with pytest.raises(R.DeadlineExceeded):
            R.backoff(1, "t")      # budget cannot cover: raises pre-sleep
        assert rt.backoff_s == 0.0
