"""Unit tests for runtime/result_cache.py: byte-accounted LRU + eviction
ladder, device->host spill round trips, catalog epochs, the volatility gate
on plan keys, and the telemetry name-stability contract additions."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import result_cache as rc
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.sql.parser import parse_sql
from dask_sql_tpu.table import Table


@pytest.fixture()
def cache(monkeypatch):
    """A fresh, generously-budgeted cache for each test."""
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "64")
    c = rc.ResultCache()
    yield c
    c.clear()


def _table(n_rows: int, fill: int = 0, with_mask: bool = False,
           with_strings: bool = False) -> Table:
    data = {"a": np.full(n_rows, fill, dtype=np.int64)}
    if with_strings:
        data["s"] = np.array(["ab", "cd"] * (n_rows // 2), dtype=object)
    t = Table.from_pydict(data)
    if with_mask:
        import jax.numpy as jnp
        col = t.columns[0]
        t.columns[0] = col.with_mask(jnp.arange(n_rows) % 2 == 0)
    return t


def _key(name: str, tables=()) -> rc.CacheKey:
    return rc.CacheKey(name, tuple(tables))


# ---------------------------------------------------------------------------
# byte accounting + LRU + the eviction ladder
# ---------------------------------------------------------------------------

def test_byte_accounting_accuracy(cache):
    t1 = _table(1024)                      # 8 KiB of int64
    t2 = _table(2048, with_mask=True)      # 16 KiB data + 2 KiB mask
    assert cache.put(_key("k1"), t1)
    assert cache.put(_key("k2"), t2)
    expected = rc._table_nbytes(t1) + rc._table_nbytes(t2)
    assert cache.device_bytes == expected
    assert cache.host_bytes == 0
    # gauge mirrors the accounting
    assert tel.REGISTRY.get_gauge("result_cache_bytes") == expected
    # replacing a key re-accounts instead of double-counting
    assert cache.put(_key("k1"), _table(512))
    assert cache.device_bytes == rc._table_nbytes(_table(512)) + \
        rc._table_nbytes(t2)


def test_lru_order_under_budget_pressure(cache, monkeypatch):
    # budget fits two 8 KiB entries; host tier off => evictions DROP
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", str(20 / 1024))
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "0")
    cache.put(_key("a"), _table(1024))
    cache.put(_key("b"), _table(1024))
    assert cache.get(_key("a")) is not None   # touch: a becomes MRU
    cache.put(_key("c"), _table(1024))        # over budget: LRU (b) drops
    assert cache.probe(_key("b")) is None
    assert cache.probe(_key("a")) == "device"
    assert cache.probe(_key("c")) == "device"
    assert cache.device_bytes <= cache.device_budget()


def test_spill_ladder_and_round_trip_equality(cache, monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", str(20 / 1024))
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "1")
    spills0 = tel.REGISTRY.get("result_cache_spills")
    orig = _table(1024, fill=7, with_mask=True, with_strings=True)
    expected = orig.to_pandas()
    cache.put(_key("a"), orig)
    cache.put(_key("b"), _table(1024))
    cache.put(_key("c"), _table(1024))
    # the ladder spilled (not dropped) the LRU device entries to host
    assert cache.probe(_key("a")) == "host"
    assert tel.REGISTRY.get("result_cache_spills") > spills0
    assert cache.host_bytes > 0
    # host hit: re-uploaded, bit-identical, and promoted back to device
    got, tier = cache.get(_key("a"))
    assert tier == "host"
    pd.testing.assert_frame_equal(got.to_pandas(), expected)
    assert cache.probe(_key("a")) == "device"


def test_host_budget_overflow_drops(cache, monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", str(10 / 1024))
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", str(10 / 1024))
    ev0 = tel.REGISTRY.get("result_cache_evictions")
    cache.put(_key("a"), _table(1024))
    cache.put(_key("b"), _table(1024))   # a spills to host
    cache.put(_key("c"), _table(1024))   # b spills; host over budget: a drops
    assert cache.probe(_key("a")) is None
    assert tel.REGISTRY.get("result_cache_evictions") > ev0
    assert cache.host_bytes <= cache.host_budget()


def test_oversized_entry_is_not_stored(cache, monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", str(4 / 1024))
    assert not cache.put(_key("big"), _table(1024))
    assert cache.stats()["entries"] == 0


def test_zero_budget_disables_cleanly(cache, monkeypatch):
    cache.put(_key("a"), _table(128))
    assert cache.stats()["entries"] == 1
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "0")
    assert not cache.enabled()
    # disabling released what was held, and get/put are no-ops
    assert cache.stats()["entries"] == 0
    assert cache.get(_key("a")) is None
    assert not cache.put(_key("a"), _table(128))


def test_cached_table_is_isolated_from_caller_mutation(cache):
    t = _table(64)
    cache.put(_key("a"), t)
    t.names[0] = "mutated"                   # caller vandalizes its copy
    got, _ = cache.get(_key("a"))
    assert got.names == ["a"]
    got.names[0] = "other"                   # hit copies are private too
    again, _ = cache.get(_key("a"))
    assert again.names == ["a"]


def test_invalidate_table_drops_referencing_entries(cache):
    inv0 = tel.REGISTRY.get("result_cache_invalidations")
    cache.put(_key("a", tables=[("root", "t1")]), _table(64))
    cache.put(_key("b", tables=[("root", "t1"), ("root", "t2")]), _table(64))
    cache.put(_key("c", tables=[("root", "t2")]), _table(64))
    assert cache.invalidate_table("root", "t1") == 2
    assert cache.probe(_key("a")) is None
    assert cache.probe(_key("b")) is None
    assert cache.probe(_key("c")) == "device"
    assert tel.REGISTRY.get("result_cache_invalidations") == inv0 + 2


# ---------------------------------------------------------------------------
# plan keys: canonicalization, epochs, volatility
# ---------------------------------------------------------------------------

def _plan(ctx, sql):
    return ctx._get_plan(parse_sql(sql)[0].query, sql)


@pytest.fixture()
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3], "b": [1.0, 2.0, 3.0]}))
    return c


def test_plan_key_stable_and_distinct(ctx):
    k1 = rc.plan_key(_plan(ctx, "SELECT a FROM t"), ctx)
    k2 = rc.plan_key(_plan(ctx, "SELECT a FROM t"), ctx)
    k3 = rc.plan_key(_plan(ctx, "SELECT b FROM t"), ctx)
    assert k1.digest == k2.digest
    assert k1.digest != k3.digest
    assert k1.tables == (("root", "t"),)


def test_plan_key_distinguishes_values_rows(ctx):
    # RelNode.explain() elides VALUES contents; the canonical serializer
    # must not (this also guards the stage-boundary digest)
    k1 = rc.plan_key(_plan(ctx, "SELECT * FROM (VALUES (1), (2)) AS v(x)"),
                     ctx)
    k2 = rc.plan_key(_plan(ctx, "SELECT * FROM (VALUES (3), (4)) AS v(x)"),
                     ctx)
    assert k1.digest != k2.digest


def test_plan_key_folds_epoch_and_uid(ctx):
    k1 = rc.plan_key(_plan(ctx, "SELECT SUM(a) AS s FROM t"), ctx)
    ctx.create_table("t", pd.DataFrame({"a": [9], "b": [9.0]}))
    k2 = rc.plan_key(_plan(ctx, "SELECT SUM(a) AS s FROM t"), ctx)
    assert k1.digest != k2.digest


def test_plan_key_volatile_ops_refuse(ctx):
    assert rc.plan_key(_plan(ctx, "SELECT RAND() AS r FROM t"), ctx) is None
    assert rc.plan_key(
        _plan(ctx, "SELECT CURRENT_TIMESTAMP AS ts FROM t"), ctx) is None


def test_plan_key_udf_refuses(ctx):
    ctx.register_function(lambda x: x + 1, "f", [("x", np.int64)], np.int64)
    assert rc.plan_key(_plan(ctx, "SELECT f(a) AS y FROM t"), ctx) is None


def test_epoch_bumps_on_every_mutation_path(ctx):
    e0 = ctx.table_epoch("root", "t")
    ctx.create_table("t", pd.DataFrame({"a": [1], "b": [1.0]}))
    e1 = ctx.table_epoch("root", "t")
    assert e1 > e0
    ctx.sql("CREATE TABLE u AS SELECT a FROM t")
    assert ctx.table_epoch("root", "u") > 0
    ctx.alter_table("u", "u2")
    assert ctx.table_epoch("root", "u2") > ctx.table_epoch("root", "u") > e1
    ctx.drop_table("u2")
    e_drop = ctx.table_epoch("root", "u2")
    assert e_drop > e1
    ctx.create_schema("s2")
    ctx.create_table("x", pd.DataFrame({"a": [1]}), schema_name="s2")
    ex = ctx.table_epoch("s2", "x")
    ctx.alter_schema("s2", "s3")
    assert ctx.table_epoch("s3", "x") > ex
    ctx.drop_schema("s3")
    assert ctx.table_epoch("s3", "x") > ex


def test_stage_table_name_uses_canonical_shape(ctx):
    """Two subplans differing only in VALUES contents must get distinct
    stage-boundary digests (the subplan cache replays by that name)."""
    from dask_sql_tpu.physical import compiled

    p1 = _plan(ctx, "SELECT * FROM (VALUES (1), (2)) AS v(x)")
    p2 = _plan(ctx, "SELECT * FROM (VALUES (3), (4)) AS v(x)")
    assert compiled._stage_table_name(p1, ctx) != \
        compiled._stage_table_name(p2, ctx)


# ---------------------------------------------------------------------------
# telemetry contract
# ---------------------------------------------------------------------------

def test_result_cache_metric_names_are_registered():
    """Append-only name-stability contract: the result-cache counters and
    gauges are part of the public metrics surface from this PR on."""
    for name in ("result_cache_hits", "result_cache_misses",
                 "result_cache_stores", "result_cache_evictions",
                 "result_cache_spills", "result_cache_invalidations",
                 "result_cache_subplan_hits", "fault_cache_populate"):
        assert name in tel.STABLE_COUNTERS
        assert tel.REGISTRY.get(name) is not None
    for name in ("result_cache_bytes", "result_cache_host_bytes"):
        assert name in tel.STABLE_GAUGES
    text = tel.REGISTRY.render_prometheus()
    assert "# TYPE dsql_result_cache_bytes gauge" in text
    assert "dsql_result_cache_hits_total" in text
