"""Unit tests for runtime/tenancy.py: identity sanitation, token-bucket
rate quota, concurrency quota, the per-tenant circuit breaker's
open/half-open/closed lifecycle, and the admission() scope's exactly-once
grant consumption + outcome classification."""
import pytest

from dask_sql_tpu.runtime import resilience as R
from dask_sql_tpu.runtime import tenancy


@pytest.fixture(autouse=True)
def fresh_registry():
    tenancy.get_registry()._reset_for_tests()
    yield
    tenancy.get_registry()._reset_for_tests()


# ---------------------------------------------------------------------------
# identity
# ---------------------------------------------------------------------------

def test_sanitize_tenant_charset():
    assert tenancy.sanitize_tenant("acme-corp_01") == "acme-corp_01"
    # padding strips; the remainder is judged on its own
    assert tenancy.sanitize_tenant("  ok  ") == "ok"
    assert tenancy.sanitize_tenant("bad tenant") is None
    assert tenancy.sanitize_tenant("a/b") is None
    assert tenancy.sanitize_tenant("x" * 65) is None
    assert tenancy.sanitize_tenant("x" * 64) == "x" * 64
    assert tenancy.sanitize_tenant(None) is None
    assert tenancy.sanitize_tenant("") is None


def test_invalid_header_maps_to_default_tenant():
    g = tenancy.get_registry().claim("not a valid tenant!!")
    assert g.tenant == tenancy.DEFAULT_TENANT
    tenancy.get_registry().release(g)


def test_tenant_scope_rejects_garbage_loudly():
    with pytest.raises(ValueError):
        with tenancy.tenant_scope("no spaces allowed"):
            pass
    with tenancy.tenant_scope("fine-name"):
        assert tenancy.current_tenant() == "fine-name"
    assert tenancy.current_tenant() is None


# ---------------------------------------------------------------------------
# quotas
# ---------------------------------------------------------------------------

def test_unlimited_by_default():
    reg = tenancy.get_registry()
    grants = [reg.claim("t") for _ in range(50)]
    for g in grants:
        reg.release(g, "ok")
    rows = tenancy.tenant_rows()
    assert rows[0]["admitted"] == 50
    assert rows[0]["inflight"] == 0


def test_rate_quota_rejects_with_honest_retry_after(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_QPS", "2")
    reg = tenancy.get_registry()
    # burst = one second of tokens (2): the third claim in the same
    # instant must be over quota
    reg.release(reg.claim("r"), "ok")
    reg.release(reg.claim("r"), "ok")
    with pytest.raises(R.TenantQuotaExceeded) as ei:
        reg.claim("r")
    # the refill pace is 2 tokens/s -> a sub-second, non-zero hint
    assert 0.0 < ei.value.retry_after_s <= 0.5
    assert tenancy.tenant_rows()[0]["quota_rejects"] == 1


def test_rate_quota_is_per_tenant(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_QPS", "1")
    reg = tenancy.get_registry()
    reg.release(reg.claim("a"), "ok")
    with pytest.raises(R.TenantQuotaExceeded):
        reg.claim("a")
    # tenant b still has its own full bucket
    reg.release(reg.claim("b"), "ok")


def test_concurrency_quota(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_CONCURRENT", "2")
    reg = tenancy.get_registry()
    g1, g2 = reg.claim("c"), reg.claim("c")
    with pytest.raises(R.TenantQuotaExceeded):
        reg.claim("c")
    reg.release(g1, "ok")
    g3 = reg.claim("c")          # a released slot is claimable again
    reg.release(g2, "ok")
    reg.release(g3, "ok")
    assert tenancy.tenant_rows()[0]["inflight"] == 0


def test_release_is_idempotent():
    reg = tenancy.get_registry()
    g = reg.claim("i")
    reg.release(g, "ok")
    reg.release(g, "ok")
    assert tenancy.tenant_rows()[0]["inflight"] == 0
    assert tenancy.tenant_rows()[0]["completed"] == 1


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------

def _fail_n(reg, tenant, n, outcome="fatal"):
    for _ in range(n):
        reg.release(reg.claim(tenant), outcome)


def test_breaker_trips_on_consecutive_fatals(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "3")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_TTL_S", "30")
    reg = tenancy.get_registry()
    _fail_n(reg, "b", 3)
    row = tenancy.tenant_rows()[0]
    assert row["circuit"] == "open"
    assert row["circuit_opens"] == 1
    with pytest.raises(R.TenantCircuitOpen) as ei:
        reg.claim("b")
    assert ei.value.retry_after_s > 0
    assert tenancy.tenant_rows()[0]["circuit_rejects"] == 1


def test_breaker_needs_consecutive_failures(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "3")
    reg = tenancy.get_registry()
    _fail_n(reg, "b", 2)
    reg.release(reg.claim("b"), "ok")      # streak broken
    _fail_n(reg, "b", 2)
    assert tenancy.tenant_rows()[0]["circuit"] == "closed"


def test_user_errors_do_not_trip(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "2")
    reg = tenancy.get_registry()
    _fail_n(reg, "b", 5, outcome="error")
    assert tenancy.tenant_rows()[0]["circuit"] == "closed"


def test_breaker_half_open_single_probe_then_close(monkeypatch):
    """After the TTL the breaker goes half-open on the quarantine
    pattern: exactly ONE probe is admitted (concurrent claims keep
    rejecting while it is in flight); a clean probe closes the circuit,
    a failed one re-arms the full TTL."""
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "2")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_TTL_S", "0.1")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_PROBE_S", "30")
    reg = tenancy.get_registry()
    _fail_n(reg, "h", 2)
    with pytest.raises(R.TenantCircuitOpen):
        reg.claim("h")
    import time
    time.sleep(0.15)                       # TTL expires -> half-open
    probe = reg.claim("h")                 # THE single probe
    assert probe.probe
    assert tenancy.tenant_rows()[0]["circuit"] == "half-open"
    with pytest.raises(R.TenantCircuitOpen):
        reg.claim("h")                     # probe in flight: still reject
    reg.release(probe, "ok")               # clean probe closes the circuit
    assert tenancy.tenant_rows()[0]["circuit"] == "closed"
    reg.release(reg.claim("h"), "ok")      # traffic flows again


def test_breaker_failed_probe_rearms(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "2")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_TTL_S", "0.1")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_PROBE_S", "30")
    reg = tenancy.get_registry()
    _fail_n(reg, "h", 2)
    import time
    time.sleep(0.15)
    probe = reg.claim("h")
    monkeypatch.setenv("DSQL_TENANT_BREAKER_TTL_S", "60")
    reg.release(probe, "fatal")            # failed probe: full TTL again
    row = tenancy.tenant_rows()[0]
    assert row["circuit"] == "open"
    assert row["circuit_opens"] == 2
    with pytest.raises(R.TenantCircuitOpen):
        reg.claim("h")


def test_breaker_off_by_default():
    reg = tenancy.get_registry()
    _fail_n(reg, "never", 50)
    assert tenancy.tenant_rows()[0]["circuit"] == "closed"


# ---------------------------------------------------------------------------
# admission() scope
# ---------------------------------------------------------------------------

def test_admission_consumes_server_preclaim_exactly_once(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_QPS", "1")
    reg = tenancy.get_registry()
    grant = reg.claim("pre")               # spends the ONLY token
    with tenancy.grant_scope(grant):
        with tenancy.admission() as g:
            assert g is grant
            assert g.consumed
    # the pre-claim was adopted, not re-claimed: no second token spent,
    # and the grant was released with outcome "ok"
    row = tenancy.tenant_rows()[0]
    assert row["admitted"] == 1
    assert row["completed"] == 1
    assert row["inflight"] == 0


def test_admission_classifies_outcomes(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "2")
    reg = tenancy.get_registry()

    def run(exc):
        with tenancy.tenant_scope("o"):
            with pytest.raises(type(exc)):
                with tenancy.admission():
                    raise exc

    run(R.FatalError("boom"))
    run(R.DeadlineExceeded("slow"))
    assert tenancy.tenant_rows()[0]["circuit"] == "open"
    reg._reset_for_tests()
    # user errors never feed the breaker
    run(ValueError("user"))
    run(ValueError("user"))
    run(ValueError("user"))
    assert tenancy.tenant_rows()[0]["circuit"] == "closed"
    assert tenancy.tenant_rows()[0]["failed"] == 3


def test_admission_nested_rides_outer_claim():
    with tenancy.tenant_scope("n"):
        with tenancy.admission():
            with tenancy.admission() as inner:
                assert inner is None       # nested: pass-through
    assert tenancy.tenant_rows()[0]["admitted"] == 1


def test_unconsumed_grant_release_feeds_nothing(monkeypatch):
    """A grant released without an outcome (DDL, pre-plan failure) frees
    its concurrency slot but neither completes nor fails the tenant."""
    monkeypatch.setenv("DSQL_TENANT_BREAKER", "1")
    reg = tenancy.get_registry()
    g = reg.claim("d")
    reg.release(g)                         # no outcome
    row = tenancy.tenant_rows()[0]
    assert row["inflight"] == 0
    assert row["completed"] == 0
    assert row["circuit"] == "closed"


def test_context_sql_tenant_stamps_report(monkeypatch):
    """Context.sql(tenant=...) flows the tenant onto the QueryReport (and
    from there the slow-query log / flight-recorder envelope); the
    default tenant stays OFF every envelope."""
    import pandas as pd

    from dask_sql_tpu.context import Context

    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3]}))
    c.sql("SELECT SUM(a) AS s FROM t", tenant="acme")
    assert c.last_report.tenant == "acme"
    assert c.last_report.to_dict()["tenant"] == "acme"
    c.sql("SELECT SUM(a) AS s FROM t")
    assert c.last_report.tenant is None
    rows = {r["tenant"]: r for r in tenancy.tenant_rows()}
    assert rows["acme"]["admitted"] == 1
    assert rows[tenancy.DEFAULT_TENANT]["admitted"] >= 1
