"""Stage-graph partitioner unit tests (physical/stages.py).

The partitioner must be a pure, deterministic function of the plan: the
compiled executor's program-cache keys flow through the boundary names, so
a nondeterministic cut would recompile on every run; and the bottom-up
greedy walk must be ancestor-independent so shared subplans cut
identically across queries (the cross-query reuse property)."""
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.physical import stages as S
from dask_sql_tpu.plan.nodes import LogicalTableScan
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture()
def ctx():
    c = Context()
    c.create_table("f", pd.DataFrame({"k": [1, 2, 3, 1], "v": [1.0, 2.0, 3.0, 4.0]}))
    c.create_table("d", pd.DataFrame({"k": [1, 2, 3], "w": [10, 20, 30]}))
    c.create_table("e", pd.DataFrame({"k": [1, 2], "z": [5, 6]}))
    return c


def _plan(c, sql):
    return c._get_plan(parse_sql(sql)[0].query)


THREE_HEAVY = ("SELECT x.k, x.s, d.w, e.z FROM "
               "(SELECT k, SUM(v) AS s FROM f GROUP BY k) x "
               "JOIN d ON x.k = d.k JOIN e ON x.k = e.k")


def _counting_namer():
    names = {}

    def make_scan(sub):
        from dask_sql_tpu.plan.nodes import Field
        name = f"s{len(names)}"
        names[name] = sub
        return LogicalTableScan(
            schema_name="__split__", table_name=name,
            schema=[Field(f"c{i}", f.stype)
                    for i, f in enumerate(sub.schema)])

    return make_scan


def test_heavy_count_and_node_weight(ctx):
    plan = _plan(ctx, THREE_HEAVY)
    assert S.heavy_count(plan) == 3  # two joins + one aggregate
    assert S.heavy_count(_plan(ctx, "SELECT k FROM f WHERE k > 1")) == 0


def test_heavy_count_deterministic(ctx):
    p1 = _plan(ctx, THREE_HEAVY)
    p2 = _plan(ctx, THREE_HEAVY)
    assert S.heavy_count(p1) == S.heavy_count(p2)


def test_partition_deterministic(ctx):
    plan = _plan(ctx, THREE_HEAVY)
    g1 = S.partition(plan, 1, _counting_namer())
    g2 = S.partition(plan, 1, _counting_namer())
    assert len(g1.stages) == len(g2.stages)
    for a, b in zip(g1.stages, g2.stages):
        assert a.deps == b.deps
        assert a.heavy == b.heavy
        assert a.plan.explain() == b.plan.explain()


def test_partition_bounds_and_topology(ctx):
    plan = _plan(ctx, THREE_HEAVY)
    for budget in (1, 2, 3):
        g = S.partition(plan, budget, _counting_namer())
        total = 0
        for i, st in enumerate(g.stages):
            # bound: no stage exceeds max(budget, single-node weight)
            assert st.heavy <= max(budget, 2)
            # topological: deps strictly precede their consumer
            assert all(d < i for d in st.deps)
            # no stage is a bare boundary/table scan (zero-work program)
            assert not isinstance(st.plan, LogicalTableScan)
            total += st.heavy
        assert total == S.heavy_count(plan)  # cuts never lose heavy nodes
        assert g.root is g.stages[-1] and g.root.scan is None
        if budget >= 3:
            assert len(g.stages) == 1  # within budget: no cuts


def test_partition_shared_subtree_is_ancestor_independent(ctx):
    """The cuts inside a subtree depend only on that subtree: the same
    subplan embedded under different parents partitions identically —
    the property cross-query stage reuse rests on."""
    qa = ("SELECT x.k, x.s, d.w FROM "
          "(SELECT k, SUM(v) AS s FROM f GROUP BY k) x "
          "JOIN d ON x.k = d.k")
    qb = ("SELECT x.k, x.s * 2 AS s2, d.w FROM "
          "(SELECT k, SUM(v) AS s FROM f GROUP BY k) x "
          "JOIN d ON x.k = d.k WHERE d.w > 15")
    ga = S.partition(_plan(ctx, qa), 1, _counting_namer())
    gb = S.partition(_plan(ctx, qb), 1, _counting_namer())
    # the shared GROUP BY subtree is cut as the first stage in both
    assert ga.stages[0].plan.explain() == gb.stages[0].plan.explain()


def test_stage_budget_env(monkeypatch):
    monkeypatch.delenv("DSQL_STAGE_HEAVY", raising=False)
    monkeypatch.delenv("DSQL_SPLIT_HEAVY", raising=False)
    assert S.stage_budget() == S.DEFAULT_STAGE_HEAVY
    assert S.stage_budget(3) == 3
    monkeypatch.setenv("DSQL_SPLIT_HEAVY", "4")  # legacy knob honored
    assert S.stage_budget() == 4
    monkeypatch.setenv("DSQL_STAGE_HEAVY", "2")  # new knob wins
    assert S.stage_budget() == 2
    assert S.stage_budget(1) == 1  # explicit override beats both
