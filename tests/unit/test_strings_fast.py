"""strings_fast: the vectorized and device LIKE bitmaps must agree with the
regex transpiler (physical/rex/ops.py sql_like_to_regex) on every pattern
they accept — differential, over adversarial string sets."""
import re

import numpy as np
import pytest

from dask_sql_tpu.ops.strings_fast import (
    device_like_bitmap, like_bitmap_vectorized, parse_like_chunks,
)
from dask_sql_tpu.physical.rex.ops import sql_like_to_regex

STRINGS = np.array([
    "", "a", "ab", "abc", "abcabc", "xabcy", "aabbcc", "abab",
    "hello world", "worldly", "special requests", "specialrequests",
    "xx special yy requests zz", "requests special", "%", "a%b", "a_b",
    "ABC", "AbC", "ivory blue", "blue ivory", "MEDIUM POLISHED TIN",
    "PROMO BRUSHED STEEL", "Customer on Complaints", "CustomerComplaints",
], dtype=object)

# the device path refuses dictionaries with >128-byte strings; keep a
# separate long entry for the cap test
LONG_STRINGS = np.append(STRINGS, np.array(["ab" * 70], dtype=object))

PATTERNS = [
    "%", "%%", "abc", "%abc", "abc%", "%abc%", "a%c", "%a%c%", "a%b%c",
    "%special%requests%", "ivory%", "%BRASS", "MEDIUM POLISHED%",
    "%Customer%Complaints%", "", "%a", "b%", "%ab%ab%", "abcabc",
    "x\\%y", "a\\%b",
]


def _regex_bitmap(d, pattern, escape, flags=0):
    rx = re.compile(sql_like_to_regex(pattern, escape), flags)
    return np.array([rx.match(s) is not None for s in d])


@pytest.mark.parametrize("pattern", PATTERNS)
def test_vectorized_matches_regex(pattern):
    escape = "\\" if "\\" in pattern else None
    d = STRINGS.astype(str)
    got = like_bitmap_vectorized(d, pattern, escape, "LIKE")
    assert got is not None
    exp = _regex_bitmap(d, pattern, escape)
    np.testing.assert_array_equal(got, exp, err_msg=pattern)


@pytest.mark.parametrize("pattern", PATTERNS)
def test_device_matches_regex(pattern):
    escape = "\\" if "\\" in pattern else None
    got = device_like_bitmap(STRINGS, pattern, escape, "LIKE")
    assert got is not None
    exp = _regex_bitmap([str(s) for s in STRINGS], pattern, escape)
    np.testing.assert_array_equal(np.asarray(got), exp, err_msg=pattern)


def test_ilike_paths():
    d = STRINGS.astype(str)
    for pattern in ("%abc%", "ABC", "%promo%", "a%C"):
        exp = _regex_bitmap(d, pattern, None, re.IGNORECASE)
        vec = like_bitmap_vectorized(d, pattern, None, "ILIKE")
        np.testing.assert_array_equal(vec, exp, err_msg=pattern)
        dev = device_like_bitmap(STRINGS, pattern, None, "ILIKE")
        np.testing.assert_array_equal(np.asarray(dev), exp, err_msg=pattern)


def test_underscore_and_similar_rejected():
    assert parse_like_chunks("a_c", None) is None
    d = STRINGS.astype(str)
    assert like_bitmap_vectorized(d, "a_c", None, "LIKE") is None
    assert like_bitmap_vectorized(d, "a%c", None, "SIMILAR") is None
    assert device_like_bitmap(STRINGS, "a_c", None, "LIKE") is None


def test_long_strings_fall_off_device_path():
    d = np.array(["x" * 200, "abc"], dtype=object)
    assert device_like_bitmap(d, "%abc%", None, "LIKE") is None
    # vectorized path has no length cap
    got = like_bitmap_vectorized(d.astype(str), "%abc%", None, "LIKE")
    np.testing.assert_array_equal(got, [False, True])


def test_random_differential():
    rng = np.random.RandomState(0)
    alphabet = list("abcx%")
    d = np.array(["".join(rng.choice(list("abcxy"), rng.randint(0, 12)))
                  for _ in range(300)], dtype=object)
    for _ in range(40):
        pattern = "".join(rng.choice(alphabet, rng.randint(0, 8)))
        exp = _regex_bitmap(d.astype(str), pattern, None)
        vec = like_bitmap_vectorized(d.astype(str), pattern, None, "LIKE")
        np.testing.assert_array_equal(vec, exp, err_msg=repr(pattern))
        dev = device_like_bitmap(d, pattern, None, "LIKE")
        np.testing.assert_array_equal(np.asarray(dev), exp,
                                      err_msg=repr(pattern))


def test_device_chunk_longer_than_dictionary_strings():
    d = np.array(["abcd", "efgh"], dtype=object)
    got = device_like_bitmap(d, "%this-is-way-longer-than-any-entry%",
                             None, "LIKE")
    np.testing.assert_array_equal(np.asarray(got), [False, False])
    got = device_like_bitmap(d, "longer-than-entries", None, "LIKE")
    np.testing.assert_array_equal(np.asarray(got), [False, False])
