"""Lockstep tests: the native (C++) optimizer must produce byte-identical
plans to the Python rule pipeline it ports (plan/optimizer.py).

The reference's planner optimizes natively (RelationalAlgebraGenerator.java:
97-224); parity here is asserted over the full TPC-H corpus plus targeted
shapes for every pass (filter pushdown, join reordering, OR factoring,
exist-test rewrites, aggregate-through-join, pruning, subquery plans).
"""
import os

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu import native as native_lib
from dask_sql_tpu.plan import optimizer as O
from dask_sql_tpu.plan.native_planner import (
    deserialize_plan, optimize_native, serialize_plan,
)
from dask_sql_tpu.sql.parser import parse_sql

pytestmark = pytest.mark.skipif(
    not native_lib.available(), reason="native library unavailable")


def _python_optimize(plan, enable_pruning=True):
    """The Python pipeline, bypassing the native fast path."""
    for p in O.PASSES:
        plan = p(plan)
    plan = O.optimize_subplans(plan)
    if enable_pruning:
        plan = O.prune_columns(plan)
        plan = O.merge_projects(plan)
    return plan


def _bind(context, sql):
    stmt = parse_sql(sql)[0]
    binder_plan = None
    from dask_sql_tpu.plan.binder import Binder
    binder_plan = Binder(context, sql).bind(stmt.query)
    return binder_plan


def _assert_lockstep(context, sql):
    plan_py = _bind(context, sql)
    plan_nat = _bind(context, sql)
    want = _python_optimize(plan_py).explain()
    native = optimize_native(plan_nat)
    assert native is not None, f"native optimizer declined: {sql[:80]}"
    assert native.explain() == want, (
        f"native/python plan divergence for: {sql[:120]}\n"
        f"--- python ---\n{want}\n--- native ---\n{native.explain()}")


@pytest.fixture(scope="module")
def tpch_context():
    from benchmarks.tpch import generate_tpch

    c = Context()
    for name, frame in generate_tpch(0.001).items():
        c.create_table(name, frame)
    return c


@pytest.fixture(scope="module")
def small_context():
    c = Context()
    rng = np.random.default_rng(0)
    c.create_table("a", pd.DataFrame({
        "id": np.arange(20), "x": rng.normal(size=20),
        "s": [f"v{i % 3}" for i in range(20)]}))
    c.create_table("b", pd.DataFrame({
        "id": np.arange(10), "y": rng.normal(size=10),
        "t": [f"w{i % 2}" for i in range(10)]}))
    c.create_table("c3", pd.DataFrame({
        "id": np.arange(10), "z": rng.normal(size=10)}))
    return c


TPCH_IDS = list(range(1, 23))


@pytest.mark.parametrize("qid", TPCH_IDS)
def test_tpch_lockstep(tpch_context, qid):
    from benchmarks.tpch import QUERIES

    _assert_lockstep(tpch_context, QUERIES[qid])


@pytest.mark.parametrize("sql", [
    # filter pushdown through project / into join sides
    "SELECT * FROM (SELECT id, x * 2 AS d FROM a) q WHERE d > 0",
    "SELECT a.id FROM a, b WHERE a.id = b.id AND a.x > 0 AND b.y < 1",
    # OR factoring (Q19 shape)
    "SELECT SUM(x) FROM a, b WHERE (a.id = b.id AND a.x > 0) "
    "OR (a.id = b.id AND b.y > 0)",
    # join reordering: comma list where neighbours connect via the third
    "SELECT COUNT(*) FROM a, c3, b WHERE a.id = b.id AND c3.id = b.id",
    # SEMI/ANTI pushdown + exist-test rewrite shape
    "SELECT id FROM a WHERE EXISTS "
    "(SELECT 1 FROM b WHERE b.id = a.id AND b.y <> a.x)",
    "SELECT id FROM a WHERE NOT EXISTS "
    "(SELECT 1 FROM b WHERE b.id = a.id AND b.id <> a.id)",
    # aggregate through join (Q13 shape)
    "SELECT a.id, COUNT(b.id) FROM a LEFT JOIN b ON a.id = b.id "
    "GROUP BY a.id",
    # scalar subquery plans optimize recursively
    "SELECT id FROM a WHERE x > (SELECT AVG(y) FROM b)",
    # set ops, sort/limit, window, distinct
    "SELECT id FROM a UNION SELECT id FROM b",
    "SELECT id FROM a INTERSECT SELECT id FROM b",
    "SELECT id FROM a EXCEPT SELECT id FROM b",
    "SELECT id, x FROM a ORDER BY x DESC NULLS FIRST LIMIT 5 OFFSET 2",
    "SELECT id, SUM(x) OVER (PARTITION BY s ORDER BY id) FROM a",
    "SELECT DISTINCT s FROM a",
    "SELECT s, COUNT(*) FILTER (WHERE x > 0) FROM a GROUP BY s",
    # correlated EXISTS with residual through HAVING
    "SELECT s, SUM(x) FROM a GROUP BY s HAVING SUM(x) > 0",
    "SELECT CASE WHEN x > 0 THEN 'p' ELSE 'n' END, id FROM a WHERE s LIKE 'v%'",
])
def test_shape_lockstep(small_context, sql):
    _assert_lockstep(small_context, sql)


def test_roundtrip_identity(tpch_context):
    """serialize -> deserialize must reproduce the plan exactly (explain)."""
    from benchmarks.tpch import QUERIES

    for qid in (1, 3, 7, 16, 21):
        plan = _bind(tpch_context, QUERIES[qid])
        wire = serialize_plan(plan)
        assert wire is not None
        assert deserialize_plan(wire).explain() == plan.explain()


def test_udf_plans_fall_back(small_context):
    """A plan carrying a Python UDF must decline native optimization and
    still execute correctly through the Python pipeline."""
    small_context.register_function(
        lambda v: v + 1, "plus_one", [("v", np.float64)], np.float64)
    sql = "SELECT plus_one(x) FROM a WHERE id < 5"
    plan = _bind(small_context, sql)
    assert serialize_plan(plan) is None
    out = small_context.sql(sql, return_futures=False)
    assert len(out) == 5


def test_executes_identically_end_to_end(small_context):
    """Same results through Context.sql with the native optimizer on/off."""
    sql = ("SELECT a.s, COUNT(*) AS n, SUM(b.y) AS sy FROM a, b "
           "WHERE a.id = b.id AND a.x > -10 GROUP BY a.s ORDER BY a.s")
    native = small_context.sql(sql, return_futures=False)
    old = os.environ.get("DSQL_NATIVE")
    os.environ["DSQL_NATIVE"] = "0"
    try:
        python = small_context.sql(sql, return_futures=False)
    finally:
        if old is None:
            os.environ.pop("DSQL_NATIVE", None)
        else:
            os.environ["DSQL_NATIVE"] = old
    pd.testing.assert_frame_equal(native, python)
