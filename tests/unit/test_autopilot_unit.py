"""Unit tests for runtime/autopilot.py: matview budget accounting,
cold-view drop, hint record/apply/two-strike revert, the fault site, the
kill switch, the zero-import tripwire, the cache-hit candidate envelope
(ranking survives a warm cache), and the DSQL_TENANT_WEIGHTS fairness
classes in the scheduler."""
import os
import subprocess
import sys
import time

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import flight_recorder as fr
from dask_sql_tpu.runtime import matview as mv
from dask_sql_tpu.runtime import scheduler as sched
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.runtime import tenancy


@pytest.fixture()
def ap_env(tmp_path, monkeypatch):
    """Armed autopilot with an explicit-tick-only daemon and a tmp
    history ring (candidates come from the flight recorder)."""
    monkeypatch.setenv("DSQL_AUTOPILOT", "1")
    monkeypatch.setenv("DSQL_AUTOPILOT_INTERVAL_S", "0")   # no daemon
    monkeypatch.setenv("DSQL_AUTOPILOT_MIN_HITS", "2")
    monkeypatch.setenv("DSQL_HISTORY_FILE", str(tmp_path / "hist.jsonl"))
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    from dask_sql_tpu.runtime import autopilot as ap
    ap._reset_for_tests()
    yield ap
    ap._reset_for_tests()


@pytest.fixture()
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame(
        {"a": [1, 2, 3, 1, 2, 3] * 20, "b": [float(i) for i in range(120)]}))
    yield c


def _warm(ctx, sql, n):
    for _ in range(n):
        ctx.sql(sql).to_pandas()


# ---------------------------------------------------------------------------
# stub reports for the feedback hook (shape mirrors telemetry.QueryReport)
# ---------------------------------------------------------------------------

class _Span:
    def __init__(self, name="query", attrs=None, children=()):
        self.name = name
        self.attrs = dict(attrs or {})
        self.children = list(children)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


class _Report:
    def __init__(self, fp, wall_ms, *, hinted=False, operators=(),
                 skew=None, cerr=None, partitions=None, cache_hit=False):
        attrs = {"plan_fp": fp}
        if hinted:
            attrs["autopilot_hinted"] = 1
        kids = []
        if partitions:
            kids.append(_Span("grace_join", {"partitions": partitions}))
        self.root = _Span("query", attrs, kids)
        self.wall_ms = float(wall_ms)
        self.cache = {"hit": cache_hit}
        self.operators = list(operators)
        self.skew_ratio = skew
        self.cost_err = cerr
        self.rows_out = 0


# ---------------------------------------------------------------------------
# kill switch + fault site
# ---------------------------------------------------------------------------

def test_kill_switch_tick_is_noop(ap_env, ctx, monkeypatch):
    monkeypatch.setenv("DSQL_AUTOPILOT", "0")
    assert not ap_env.enabled()
    assert ap_env.tick(ctx) == {}
    assert ap_env.journal_rows() == []


def test_fault_site_degrades_tick_to_journaled_noop(ap_env, ctx):
    before = tel.REGISTRY.get("fault_autopilot") or 0
    with faults.inject("autopilot:1+"):
        out = ap_env.tick(ctx)
    assert out == {"faulted": True}
    rows = ap_env.journal_rows()
    assert rows and rows[-1]["action"] == "tick_fault"
    assert (tel.REGISTRY.get("fault_autopilot") or 0) > before
    # nothing was created, nothing is managed — pure no-op
    assert ap_env.engine_section()["managedViews"] == []


# ---------------------------------------------------------------------------
# matview loop: create under budget, skip over budget, drop when cold
# ---------------------------------------------------------------------------

def test_tick_creates_top_candidate(ap_env, ctx):
    _warm(ctx, "SELECT a, SUM(b) AS s FROM t GROUP BY a", 3)
    before = tel.REGISTRY.get("autopilot_mv_creates")
    out = ap_env.tick(ctx)
    assert out["created"] == 1
    assert tel.REGISTRY.get("autopilot_mv_creates") == before + 1
    sec = ap_env.engine_section()
    assert len(sec["managedViews"]) == 1
    name = sec["managedViews"][0]
    assert name.startswith("auto_mv_")
    # the view is a real registry entry queryable by name
    got = ctx.sql(f"SELECT * FROM {name}").to_pandas()
    assert len(got) == 3
    rows = ap_env.journal_rows()
    assert any(r["action"] == "mv_create" and r["bytes"] > 0 for r in rows)
    # a second tick must NOT re-create the same shape (managed-fp guard
    # across the shape-mode/value-mode fingerprint duality)
    assert ap_env.tick(ctx)["created"] == 0


def test_system_autopilot_table(ap_env, ctx):
    _warm(ctx, "SELECT a, SUM(b) AS s FROM t GROUP BY a", 3)
    ap_env.tick(ctx)
    got = ctx.sql(
        "SELECT action, fingerprint, bytes FROM system.autopilot"
    ).to_pandas()
    assert "mv_create" in set(got["action"])
    row = got[got["action"] == "mv_create"].iloc[0]
    assert row["fingerprint"] and row["bytes"] > 0


def test_budget_accounting(ap_env, ctx, monkeypatch):
    _warm(ctx, "SELECT a, SUM(b) AS s FROM t GROUP BY a", 3)
    # a zero budget: the estimated state bytes exceed it -> skip, journal
    monkeypatch.setenv("DSQL_AUTOPILOT_MV_MB", "0")
    out = ap_env.tick(ctx)
    assert out["created"] == 0
    rows = ap_env.journal_rows()
    assert any(r["action"] == "mv_skip" and r["trigger"] == "budget"
               for r in rows)
    assert ap_env.engine_section()["mvUsedBytes"] == 0
    # budget restored: the same candidate materializes and the used-bytes
    # ledger stays within budget
    monkeypatch.setenv("DSQL_AUTOPILOT_MV_MB", "64")
    assert ap_env.tick(ctx)["created"] == 1
    sec = ap_env.engine_section()
    assert 0 < sec["mvUsedBytes"] <= sec["mvBudgetBytes"]


def test_cold_view_drop_and_serve_keeps_warm(ap_env, ctx):
    _warm(ctx, "SELECT a, SUM(b) AS s FROM t GROUP BY a", 3)
    now = time.time()
    assert ap_env.tick(ctx, now=now)["created"] == 1
    name = ap_env.engine_section()["managedViews"][0]
    schema = ctx.schema_name
    # a serve advances the warmth clock: not cold at +400s
    reg = mv.get_registry(ctx)
    reg.views[(schema, name)].serves += 1
    assert ap_env.tick(ctx, now=now + 400)["dropped"] == 0
    assert name in ap_env.engine_section()["managedViews"]
    # no further serves: cold at +800s -> dropped, books settled
    before = tel.REGISTRY.get("autopilot_mv_drops")
    out = ap_env.tick(ctx, now=now + 800)
    assert out["dropped"] == 1
    assert tel.REGISTRY.get("autopilot_mv_drops") == before + 1
    assert ap_env.engine_section()["managedViews"] == []
    assert ap_env.engine_section()["mvUsedBytes"] == 0
    assert name not in ctx.schema[schema].tables
    rows = ap_env.journal_rows()
    drop = [r for r in rows if r["action"] == "mv_drop"]
    assert drop and drop[-1]["bytes"] > 0


def test_unparseable_candidate_blacklisted_once(ap_env, ctx):
    fp = "deadbeef" * 8
    fr._observe_stat(fp, nbytes=1024, rows=10, ms=50.0)
    fr._observe_stat(fp, nbytes=1024, rows=10, ms=50.0)
    fr._append(fr.history_path(),
               {"kind": "query", "plan_fp": fp, "query": "NOT REAL SQL ("})
    assert ap_env.tick(ctx)["created"] == 0
    rows = [r for r in ap_env.journal_rows() if r["action"] == "mv_reject"]
    assert len(rows) == 1 and rows[0]["fingerprint"] == fp
    # the blacklist holds: no second reject for the same fingerprint
    ap_env.tick(ctx)
    rows = [r for r in ap_env.journal_rows() if r["action"] == "mv_reject"]
    assert len(rows) == 1


# ---------------------------------------------------------------------------
# cache-hit candidate envelope: ranking survives a warm cache
# ---------------------------------------------------------------------------

def test_candidate_hits_accrue_through_warm_cache(ap_env, ctx):
    """A result-cache hit used to record NOTHING, so a warm cache starved
    system.view_candidates of exactly the queries most worth
    materializing.  Hits now accrue through a lightweight count-only
    envelope (outcome="cache_hit", zero device ms)."""
    sql = "SELECT a, SUM(b) AS s FROM t GROUP BY a"
    ctx.sql(sql).to_pandas()                      # miss: full envelope
    cands = mv.view_candidate_rows(ctx)
    assert cands and cands[0]["hits"] == 1
    fp = cands[0]["fingerprint"]
    ewma_before = cands[0]["ewma_ms"]
    _warm(ctx, sql, 2)                            # warm: cache hits
    events = fr.read_events(kind="query")
    assert [e["outcome"] for e in events[-2:]] == ["cache_hit", "cache_hit"]
    cands = {c["fingerprint"]: c for c in mv.view_candidate_rows(ctx)}
    assert cands[fp]["hits"] == 3
    # count-only accrual: the near-zero served-from-memory wall must not
    # crater the recompute-cost term of the ranking score
    assert cands[fp]["ewma_ms"] == pytest.approx(ewma_before)
    assert cands[fp]["score"] == pytest.approx(3 * ewma_before)


# ---------------------------------------------------------------------------
# adaptive re-planning: record, apply, judge, two-strike revert
# ---------------------------------------------------------------------------

def test_hint_record_flips_measured_decisions(ap_env, ctx):
    before = tel.REGISTRY.get("autopilot_hints_recorded")
    rep = _Report("fp-skew", 100.0, skew=5.0, partitions=8,
                  operators=["spmd_join=broadcast build=left rows=10",
                             "groupby=hash rows=10 ndv=3"])
    ap_env.on_query_complete(rep)
    entry = ap_env.get_hint("fp-skew")
    assert entry is not None and entry["state"] == "active"
    assert entry["hints"] == {"join": "exchange", "groupby": "sorted",
                              "partitions": 16}
    assert entry["baseline_ms"] == 100.0
    assert tel.REGISTRY.get("autopilot_hints_recorded") == before + 1
    rows = ap_env.journal_rows()
    assert rows[-1]["action"] == "hint_record"
    assert "skew_ratio=5" in rows[-1]["trigger"]


def test_hint_below_threshold_records_nothing(ap_env):
    ap_env.on_query_complete(
        _Report("fp-ok", 100.0, skew=1.2, cerr=0.3,
                operators=["groupby=hash rows=10"]))
    assert ap_env.get_hint("fp-ok") is None


def test_cache_hit_and_error_runs_are_not_samples(ap_env):
    ap_env.on_query_complete(
        _Report("fp-c", 1.0, skew=9.0, cache_hit=True,
                operators=["groupby=hash rows=10"]))
    assert ap_env.get_hint("fp-c") is None
    ap_env.on_query_complete(
        _Report("fp-e", 1.0, skew=9.0, operators=["groupby=hash rows=1"]),
        error=RuntimeError("boom"))
    assert ap_env.get_hint("fp-e") is None


def test_hint_applies_to_next_execution(ap_env, ctx):
    ap_env.on_query_complete(
        _Report("fp-a", 100.0, skew=5.0, partitions=4))
    before = tel.REGISTRY.get("autopilot_hints_applied")
    ap_env.begin_query("fp-a", ctx)
    try:
        assert ap_env.current_hint("partitions") == 8
        assert ap_env.current_hint("join") is None
    finally:
        ap_env.end_query()
    assert ap_env.current_hint("partitions") is None
    assert tel.REGISTRY.get("autopilot_hints_applied") == before + 1


def test_two_strike_revert(ap_env, ctx):
    ap_env.on_query_complete(_Report("fp-r", 100.0, skew=5.0, partitions=4))
    # strike 1: a hinted run measurably slower than the 100ms baseline
    ap_env.on_query_complete(_Report("fp-r", 150.0, hinted=True))
    entry = ap_env.get_hint("fp-r")
    assert entry["state"] == "active" and entry["strikes"] == 1
    assert any(r["action"] == "hint_strike" for r in ap_env.journal_rows())
    # a faster run resets the strikes — one bad sample is not a verdict
    ap_env.on_query_complete(_Report("fp-r", 80.0, hinted=True))
    entry = ap_env.get_hint("fp-r")
    assert entry["strikes"] == 0 and entry["verdict"] == "faster"
    assert entry["hinted_ms"] == 80.0
    # two consecutive slower runs revert the hint permanently
    before = tel.REGISTRY.get("autopilot_hints_reverted")
    ap_env.on_query_complete(_Report("fp-r", 150.0, hinted=True))
    ap_env.on_query_complete(_Report("fp-r", 150.0, hinted=True))
    entry = ap_env.get_hint("fp-r")
    assert entry["state"] == "reverted" and entry["strikes"] == 2
    assert tel.REGISTRY.get("autopilot_hints_reverted") == before + 1
    assert any(r["action"] == "hint_revert" for r in ap_env.journal_rows())
    # a reverted hint never applies again
    ap_env.begin_query("fp-r", ctx)
    try:
        assert ap_env.current_hint("partitions") is None
    finally:
        ap_env.end_query()
    # ...and later samples leave the tombstone alone
    ap_env.on_query_complete(_Report("fp-r", 500.0, skew=9.0, partitions=4))
    assert ap_env.get_hint("fp-r")["state"] == "reverted"


def test_hints_cross_process_via_file(ap_env, tmp_path, monkeypatch):
    """The hint store follows the kvstore discipline: a second process
    (fresh module state) sees the same active hint."""
    ap_env.on_query_complete(_Report("fp-x", 100.0, skew=5.0, partitions=4))
    path = ap_env.hints_path()
    assert path and os.path.exists(path)
    code = (
        "from dask_sql_tpu.runtime import autopilot as ap\n"
        "e = ap.get_hint('fp-x')\n"
        "assert e and e['state'] == 'active' "
        "and e['hints'] == {'partitions': 8}, e\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stderr.decode()


def test_engine_section_shape(ap_env, ctx):
    ap_env.on_query_complete(_Report("fp-s", 100.0, skew=5.0, partitions=4))
    sec = ap_env.engine_section()
    assert sec["enabled"] is True
    assert sec["hintsActive"] == 1 and sec["hintsReverted"] == 0
    assert sec["actions"] >= 1
    assert sec["lastAction"]["action"] == "hint_record"
    assert sec["mvBudgetBytes"] == 64 * 2**20


# ---------------------------------------------------------------------------
# the zero-import disabled path
# ---------------------------------------------------------------------------

def test_disabled_query_never_imports_autopilot():
    """With DSQL_AUTOPILOT unset an end-to-end query must leave
    runtime.autopilot out of sys.modules entirely — the tripwire that
    keeps the kill switch bit-for-bit."""
    code = (
        "import sys\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3]})\n"
        "assert c.sql('SELECT SUM(a) AS s FROM t').to_pylist() == [[6]]\n"
        "assert 'dask_sql_tpu.runtime.autopilot' not in sys.modules, \\\n"
        "    'disabled path imported the autopilot'\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()


# ---------------------------------------------------------------------------
# DSQL_TENANT_WEIGHTS: per-tenant fairness classes in the scheduler
# ---------------------------------------------------------------------------

def test_tenant_weights_parsing(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_WEIGHTS", "Gold:8, default:1, bad,z:-3")
    w = sched.tenant_weights()
    assert w["gold"] == 8.0 and w["default"] == 1.0
    assert "bad" not in w
    assert w["z"] == 0.01          # clamped: zero/negative would starve
    monkeypatch.delenv("DSQL_TENANT_WEIGHTS")
    assert sched.tenant_weights() == {}
    assert sched._fairness_tenant() is None


def test_tenant_class_keys_and_weights(monkeypatch):
    monkeypatch.setenv("DSQL_TENANT_WEIGHTS", "gold:8,default:1")
    t_gold = sched.Ticket("interactive", 0, 0.0, tenant="gold")
    t_plain = sched.Ticket("interactive", 0, 0.0)
    assert sched.WorkloadManager._class_key(t_gold) == "interactive@gold"
    assert sched.WorkloadManager._class_key(t_plain) == "interactive"
    w = sched.WorkloadManager._weight_of
    assert w("interactive@gold") == sched.WEIGHTS["interactive"] * 8.0
    # an unlisted tenant inherits the "default" entry
    assert w("batch@bronze") == sched.WEIGHTS["batch"] * 1.0
    assert w("interactive") == sched.WEIGHTS["interactive"]


def test_tenant_counters_reconcile(monkeypatch):
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "1")
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "0")
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "0")
    monkeypatch.setenv("DSQL_TENANT_WEIGHTS", "gold:8,default:1")
    mgr = sched.WorkloadManager()
    names = [f"sched_{k}_tenant_gold"
             for k in ("submitted", "admitted", "rejected", "timeout")]
    before = {n: tel.REGISTRY.get(n) or 0 for n in names}
    with tenancy.tenant_scope("gold"):
        t = mgr.acquire("interactive", 0)
        assert t.admitted
        # zero queue depth: a second acquire rejects immediately
        with pytest.raises(Exception):
            mgr.acquire("interactive", 0)
        mgr.release(t)
    d = {n: (tel.REGISTRY.get(n) or 0) - before[n] for n in names}
    assert d["sched_submitted_tenant_gold"] == 2
    assert d["sched_admitted_tenant_gold"] == 1
    assert d["sched_rejected_tenant_gold"] == 1
    # per-tenant books balance: submitted == admitted + rejected + timeout
    assert (d["sched_submitted_tenant_gold"]
            == d["sched_admitted_tenant_gold"]
            + d["sched_rejected_tenant_gold"]
            + d["sched_timeout_tenant_gold"])
    # the priority-keyed counters (the pre-existing contract) still moved
    assert (tel.REGISTRY.get("sched_admitted_interactive") or 0) > 0


def test_unarmed_tenant_keys_stay_priority_only(monkeypatch):
    monkeypatch.delenv("DSQL_TENANT_WEIGHTS", raising=False)
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    mgr = sched.WorkloadManager()
    with tenancy.tenant_scope("gold"):
        t = mgr.acquire("interactive", 0)
    assert t.tenant is None
    assert sched.WorkloadManager._class_key(t) == "interactive"
    mgr.release(t)
    assert set(mgr._waiting) <= set(sched.PRIORITIES)
