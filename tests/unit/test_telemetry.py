"""Unit tests for runtime/telemetry.py: span nesting + exception paths,
registry atomicity/snapshot/reset, prometheus rendering, chrome-trace
export, the deprecated dict aliases, and worker-thread re-entry."""
import json
import threading

import pytest

from dask_sql_tpu.runtime import telemetry as tel


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_builds_tree():
    with tel.trace_scope("SELECT 1") as trace:
        assert trace is not None
        with tel.span("parse"):
            pass
        with tel.span("execute"):
            with tel.span("compile"):
                pass
            with tel.span("materialize"):
                pass
    names = [s.name for s in trace.root.walk()]
    assert names == ["query", "parse", "execute", "compile", "materialize"]
    execute = trace.root.children[1]
    assert [c.name for c in execute.children] == ["compile", "materialize"]
    # every span closed with a wall time
    for s in trace.root.walk():
        assert s.t1 is not None
        assert s.wall_ms >= 0.0


def test_span_exception_path_marks_and_reraises():
    with pytest.raises(ValueError):
        with tel.trace_scope("boom") as trace:
            with tel.span("execute"):
                raise ValueError("boom")
    # the span AND the root both closed and carry the error class
    execute = trace.root.children[0]
    assert execute.t1 is not None
    assert execute.attrs["error"] == "ValueError"
    assert trace.root.attrs["error"] == "ValueError"
    # the report still exists for a failed query
    assert trace.report is not None
    assert trace.report.phases["execute"] >= 0.0
    # and telemetry state fully unwound: no trace leaks to the next query
    assert tel.current_trace() is None
    assert tel.current_span() is None


def test_span_outside_trace_is_noop():
    assert tel.current_trace() is None
    with tel.span("orphan") as s:
        assert s is None
    tel.annotate(ignored=True)  # must not raise


def test_nested_trace_scope_rides_outer():
    with tel.trace_scope("outer") as outer:
        with tel.trace_scope("inner") as inner:
            assert inner is None  # one trace per outermost query
            with tel.span("execute"):
                pass
    assert outer.report.phases["execute"] >= 0.0


def test_annotate_targets_innermost_open_span():
    with tel.trace_scope("q") as trace:
        with tel.span("execute"):
            with tel.span("stage"):
                tel.annotate(index=3, cache_hit=True)
    stage = trace.root.children[0].children[0]
    assert stage.attrs == {"index": 3, "cache_hit": True}


def test_scoped_reentry_attaches_worker_spans():
    """Worker threads re-enter the trace via scoped() (the stage-graph
    pool pattern); their spans land under the chosen parent."""
    with tel.trace_scope("q") as trace:
        with tel.span("execute") as parent:
            caught = []

            def worker(i):
                with tel.scoped(trace, parent):
                    with tel.span("stage", index=i):
                        caught.append(tel.current_trace() is trace)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
    assert caught == [True] * 8
    stages = [s for s in trace.root.walk() if s.name == "stage"]
    assert len(stages) == 8
    assert sorted(s.attrs["index"] for s in stages) == list(range(8))
    # concurrent child append lost nothing
    assert trace.report.span_count("stage") == 8


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_increments_are_atomic_across_threads():
    reg = tel.MetricsRegistry()
    N, T = 2000, 8

    def bump():
        for _ in range(N):
            reg.inc("c")

    threads = [threading.Thread(target=bump) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.get("c") == N * T


def test_registry_snapshot_and_reset():
    reg = tel.MetricsRegistry(seed=("a", "b"))
    reg.inc("a", 3)
    reg.observe("h_ms", 12.0)
    snap = reg.snapshot()
    assert snap["counters"] == {"a": 3, "b": 0}
    assert snap["histograms"]["h_ms"]["count"] == 1
    assert snap["histograms"]["h_ms"]["sum"] == 12.0
    reg.reset()
    snap = reg.snapshot()
    # seeded keys survive a reset at zero; histograms clear
    assert snap["counters"] == {"a": 0, "b": 0}
    assert snap["histograms"] == {}


def test_histogram_is_bounded_and_buckets_correctly():
    reg = tel.MetricsRegistry()
    for v in (0.5, 3.0, 3.0, 40.0, 10 ** 9):
        reg.observe("h", v)
    h = reg.snapshot()["histograms"]["h"]
    assert h["count"] == 5
    buckets = dict(h["buckets"])
    assert buckets[1] == 1          # 0.5
    assert buckets[5] == 2          # 3.0 x2
    assert buckets[50] == 1         # 40.0
    assert h["overflow"] == 1       # 1e9 beyond the last bound
    # bounded: observing more values never grows the structure
    assert len(h["buckets"]) == len(tel._BUCKETS_MS)


def test_prometheus_render_shape():
    reg = tel.MetricsRegistry(seed=("compiles",))
    reg.inc("compiles", 2)
    reg.observe("query_wall_ms", 7.0)
    text = reg.render_prometheus()
    assert "# TYPE dsql_compiles_total counter" in text
    assert "dsql_compiles_total 2" in text
    assert "# TYPE dsql_query_wall_ms histogram" in text
    assert 'dsql_query_wall_ms_bucket{le="+Inf"} 1' in text
    assert "dsql_query_wall_ms_sum 7" in text
    assert "dsql_query_wall_ms_count 1" in text
    # cumulative le-buckets are monotone
    counts = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
              if line.startswith("dsql_query_wall_ms_bucket")]
    assert counts == sorted(counts)


def test_global_registry_seeds_stable_names():
    counters = tel.REGISTRY.counters()
    for name in tel.STABLE_COUNTERS:
        assert name in counters, f"stable counter {name} not seeded"


# ---------------------------------------------------------------------------
# deprecated aliases
# ---------------------------------------------------------------------------

def test_compiled_stats_alias_reads_registry():
    from dask_sql_tpu.physical import compiled
    before = compiled.stats["compiles"]
    tel.inc("compiles")
    try:
        assert compiled.stats["compiles"] == before + 1
        snap = dict(compiled.stats)
        assert snap["compiles"] == before + 1
        assert "stage_graphs" in snap
        with pytest.raises(KeyError):
            compiled.stats["no_such_counter"]
    finally:
        tel.REGISTRY.set("compiles", before)


def test_exec_profile_is_thread_local():
    tel.exec_profile().clear()
    tel.exec_profile()["device_ms"] = 1.5
    seen = {}

    def other():
        seen["empty"] = dict(tel.exec_profile())
        tel.exec_profile()["device_ms"] = 99.0

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["empty"] == {}          # the other thread saw ITS OWN dict
    assert tel.exec_profile()["device_ms"] == 1.5
    tel.exec_profile().clear()


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------

def test_report_phase_aggregation_and_counters_delta():
    with tel.trace_scope("q") as trace:
        tel.inc("compiles")
        with tel.span("parse"):
            pass
        with tel.span("execute"):
            with tel.span("compile"):
                pass
    try:
        rep = trace.report
        assert rep is not None
        assert set(rep.phases) >= {"parse", "execute", "compile"}
        # phases measured from spans can never exceed the query wall
        assert rep.phases["parse"] + rep.phases["execute"] <= rep.wall_ms
        assert rep.counters.get("compiles") == 1
        # the trace's own bookkeeping (queries/query_wall_ms) lands AFTER
        # the report snapshot: the per-query delta is engine work only
        assert "queries" not in rep.counters
    finally:
        tel.REGISTRY.inc("compiles", -1)


def test_report_render_and_dict():
    with tel.trace_scope("SELECT x FROM t") as trace:
        with tel.span("execute"):
            tel.annotate(cache_hit=True)
        trace.root.attrs["rows_out"] = 7
    rep = trace.report
    assert rep.rows_out == 7
    d = rep.to_dict()
    assert d["query"] == "SELECT x FROM t"
    assert d["spans"]["children"][0]["attrs"] == {"cache_hit": True}
    text = rep.render()
    assert "SELECT x FROM t" in text
    assert "execute" in text and "cache_hit=True" in text


def test_chrome_trace_export_shape():
    with tel.trace_scope("q") as trace:
        with tel.span("execute"):
            with tel.span("stage", index=0):
                pass
    blob = trace.report.to_chrome_trace()
    events = blob["traceEvents"]
    assert [e["name"] for e in events] == ["query", "execute", "stage"]
    for e in events:
        assert e["ph"] == "X"
        assert e["ts"] >= 0 and e["dur"] >= 0
    assert events[2]["args"] == {"index": 0}
    json.dumps(blob)  # must be JSON-serializable as-is


def test_chrome_trace_file_export(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_CHROME_TRACE_DIR", str(tmp_path))
    with tel.trace_scope("q"):
        with tel.span("execute"):
            pass
    files = list(tmp_path.glob("*.trace.json"))
    assert len(files) == 1
    blob = json.loads(files[0].read_text())
    assert blob["traceEvents"][0]["name"] == "query"


def test_slow_query_log_counter(monkeypatch, caplog):
    import logging
    before = tel.REGISTRY.get("slow_queries")
    monkeypatch.setenv("DSQL_SLOW_QUERY_MS", "0")
    with caplog.at_level(logging.WARNING,
                         logger="dask_sql_tpu.runtime.telemetry"):
        with tel.trace_scope("SELECT slow"):
            pass
    assert tel.REGISTRY.get("slow_queries") == before + 1
    assert any("slow query" in r.message for r in caplog.records)


def test_slow_query_log_is_self_contained(monkeypatch, caplog):
    """A slow-log line carries tier, cacheHit and priority so triage
    needs no query replay."""
    import logging
    monkeypatch.setenv("DSQL_SLOW_QUERY_MS", "0")
    with caplog.at_level(logging.WARNING,
                         logger="dask_sql_tpu.runtime.telemetry"):
        with tel.trace_scope("SELECT triage"):
            with tel.span("queued", priority="batch"):
                pass
            with tel.span("execute", tier="compiled"):
                pass
    msg = next(r.message for r in caplog.records
               if "SELECT triage" in r.message)
    assert "tier: compiled" in msg
    assert "cacheHit: False" in msg
    assert "priority: batch" in msg


def test_last_report_is_thread_local():
    with tel.trace_scope("mine"):
        pass
    assert tel.last_report().query == "mine"
    seen = {}

    def other():
        with tel.trace_scope("theirs"):
            pass
        seen["q"] = tel.last_report().query

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["q"] == "theirs"
    assert tel.last_report().query == "mine"  # not clobbered


# ---------------------------------------------------------------------------
# node recorder
# ---------------------------------------------------------------------------

def test_node_recorder_accumulates_per_node():
    class N:  # stand-in plan node
        pass

    a, b = N(), N()
    with tel.record_nodes() as rec:
        assert tel.active_node_recorder() is rec
        rec.add(a, 1.0, 10)
        rec.add(a, 2.0, 10)
        rec.add(b, 5.0, 3)
    assert tel.active_node_recorder() is None
    assert rec.get(a) == [3.0, 20, 2]
    assert rec.get(b) == [5.0, 3, 1]
    assert rec.get(N()) is None
