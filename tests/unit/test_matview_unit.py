"""Unit tests for the materialized-view subsystem (runtime/matview.py):
parser/AST forms, maintainability analysis, the registry's delta/tombstone
seam, and append_rows coercion — no full-query oracle runs (those live in
tests/integration/test_matview.py)."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import matview as mv
from dask_sql_tpu.runtime.resilience import UserError
from dask_sql_tpu.sql import ast as A
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture(autouse=True)
def _cache_on(monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    yield


# ---------------------------------------------------------------------------
# parser / AST
# ---------------------------------------------------------------------------

def test_parse_create_matview():
    (stmt,) = parse_sql(
        "CREATE MATERIALIZED VIEW v AS SELECT a, SUM(b) FROM t GROUP BY a")
    assert isinstance(stmt, A.CreateMaterializedView)
    assert stmt.name == ["v"]
    assert not stmt.or_replace and not stmt.if_not_exists


def test_parse_create_matview_or_replace_if_not_exists():
    (s1,) = parse_sql("CREATE OR REPLACE MATERIALIZED VIEW s.v AS "
                      "(SELECT 1 AS x)")
    assert isinstance(s1, A.CreateMaterializedView)
    assert s1.or_replace and s1.name == ["s", "v"]
    (s2,) = parse_sql("CREATE MATERIALIZED VIEW IF NOT EXISTS v AS "
                      "SELECT 1 AS x")
    assert s2.if_not_exists


def test_parse_drop_refresh_matview():
    (d,) = parse_sql("DROP MATERIALIZED VIEW IF EXISTS v")
    assert isinstance(d, A.DropMaterializedView) and d.if_exists
    (r,) = parse_sql("REFRESH MATERIALIZED VIEW s.v")
    assert isinstance(r, A.RefreshMaterializedView)
    assert r.name == ["s", "v"]


def test_parse_insert_forms():
    (i1,) = parse_sql("INSERT INTO t VALUES (1, 'a'), (2, NULL)")
    assert isinstance(i1, A.InsertInto)
    assert i1.columns is None
    (i2,) = parse_sql("INSERT INTO t (a, b) VALUES (1, 2)")
    assert i2.columns == ["a", "b"]
    (i3,) = parse_sql("INSERT INTO t SELECT * FROM s")
    assert i3.columns is None
    # '(' after the table name may open a parenthesized query, not a
    # column list
    (i4,) = parse_sql("INSERT INTO t (SELECT * FROM s)")
    assert i4.columns is None


def test_plain_create_view_still_parses():
    (stmt,) = parse_sql("CREATE VIEW v AS SELECT 1 AS x")
    assert isinstance(stmt, A.CreateTableAs) and stmt.view


# ---------------------------------------------------------------------------
# maintainability analysis
# ---------------------------------------------------------------------------

def _ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "k": ["a", "b", "a"], "x": [1.0, 2.0, 3.0], "y": [1, 2, 3]}))
    return c


def _shape_of(c, sql):
    plan = c._get_plan(parse_sql(sql)[0].query, sql)
    return mv._analyze(plan, c)


@pytest.mark.parametrize("query,kind", [
    ("SELECT k, SUM(x) AS s FROM t GROUP BY k", "agg"),
    ("SELECT k, AVG(y) AS a, COUNT(*) AS n FROM t GROUP BY k", "agg"),
    ("SELECT MIN(x) AS mn, MAX(x) AS mx FROM t", "agg"),
    ("SELECT k, x FROM t WHERE y > 1", "append"),
    ("SELECT UPPER(k) AS ku FROM t", "append"),
])
def test_analyze_maintainable(query, kind):
    c = _ctx()
    shape, reason = _shape_of(c, query)
    assert shape is not None, reason
    assert shape.kind == kind


@pytest.mark.parametrize("query,needle", [
    # a COUNT(DISTINCT) mixed with other aggregates exceeds the refcounted
    # value state (ISSUE 20 maintains only the single-agg form)
    ("SELECT COUNT(DISTINCT k) AS n, SUM(x) AS s FROM t", "DISTINCT"),
    # outer joins can retract rows; only INNER join trees maintain
    ("SELECT a.k FROM t a LEFT JOIN t b ON a.k = b.k", "INNER"),
    ("SELECT k, x FROM t ORDER BY x LIMIT 2", "ORDER BY"),
    ("SELECT k FROM (SELECT k, SUM(x) AS s FROM t GROUP BY k) "
     "GROUP BY k", "nested aggregates"),
])
def test_analyze_full_recompute_with_reason(query, needle):
    c = _ctx()
    shape, reason = _shape_of(c, query)
    assert shape is None
    assert needle.lower() in reason.lower()


def test_analyze_having_above_agg_is_maintainable():
    c = _ctx()
    shape, reason = _shape_of(
        c, "SELECT k, SUM(x) AS s FROM t GROUP BY k HAVING SUM(x) > 1")
    assert shape is not None, reason
    assert shape.kind == "agg" and shape.above


def test_analyze_order_by_above_agg_is_maintainable():
    # sorting the (small) aggregate output re-runs per refresh: fine
    c = _ctx()
    shape, reason = _shape_of(
        c, "SELECT k, SUM(x) AS s FROM t GROUP BY k ORDER BY k")
    assert shape is not None, reason


# ---------------------------------------------------------------------------
# registry delta/tombstone seam
# ---------------------------------------------------------------------------

def test_delta_recorded_only_with_dependent_views():
    c = _ctx()
    # no registry at all until the first CREATE MATERIALIZED VIEW
    assert c.__dict__.get("_matview_registry") is None
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    reg = c._matview_registry
    key = ("root", "t")
    c.append_rows("t", [("z", 9.0, 9)])
    assert len(reg.deltas[key]) == 1
    # a table with no dependent view records nothing
    c.create_table("u", pd.DataFrame({"a": [1]}))
    c.append_rows("u", [(2,)])
    assert ("root", "u") not in reg.deltas


def test_overwrite_tombstones_and_clears_deltas():
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    reg = c._matview_registry
    key = ("root", "t")
    c.append_rows("t", [("z", 9.0, 9)])
    assert reg.deltas.get(key)
    c.create_table("t", pd.DataFrame({
        "k": ["q"], "x": [0.0], "y": [0]}))
    assert key not in reg.deltas
    assert reg.tombstones[key] == c.table_epoch("root", "t")


def test_delta_log_overflow_compacts_before_tombstoning(monkeypatch):
    from dask_sql_tpu.runtime import telemetry as tel
    monkeypatch.setattr(mv, "MAX_DELTAS", 3)
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    reg = c._matview_registry
    key = ("root", "t")
    before = tel.REGISTRY.get("mv_delta_compactions", 0)
    for i in range(5):
        c.append_rows("t", [("z", float(i), i)])
    # appends 1-3 filled the log; append 4 hit the cap but the unconsumed
    # tail coalesced into one record instead of tombstoning, so the view
    # keeps maintaining incrementally
    assert tel.REGISTRY.get("mv_delta_compactions", 0) > before
    assert key not in reg.tombstones
    assert 0 < len(reg.deltas[key]) <= 3
    out = c.sql("SELECT SUM(s) AS tot FROM v", return_futures=False)
    base = c.sql("SELECT SUM(x) AS tot FROM t", return_futures=False)
    assert float(out["tot"][0]) == float(base["tot"][0])


def test_delta_log_overflow_degrades_to_tombstone(monkeypatch):
    # compaction may only merge records ABOVE every dependent view's
    # watermark; a record a laggard view still needs is unmergeable, so a
    # capped log straddling two watermarks still degrades to a tombstone
    monkeypatch.setattr(mv, "MAX_DELTAS", 1)
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW v1 AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    c.sql("CREATE MATERIALIZED VIEW v2 AS SELECT k, SUM(y) AS s FROM t "
          "GROUP BY k")
    reg = c._matview_registry
    key = ("root", "t")
    c.append_rows("t", [("z", 9.0, 9)])
    c.sql("REFRESH MATERIALIZED VIEW v1")  # v1 consumes; v2 lags
    c.append_rows("t", [("w", 8.0, 8)])    # log full, tail unmergeable
    assert reg.tombstones[key] > 0
    # both views still refresh correctly (full recompute)
    out = c.sql("SELECT SUM(s) AS tot FROM v2", return_futures=False)
    base = c.sql("SELECT SUM(y) AS tot FROM t", return_futures=False)
    assert float(out["tot"][0]) == float(base["tot"][0])


def test_kill_switch_rejects_statements_and_degrades_deltas(monkeypatch):
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, SUM(x) AS s FROM t "
          "GROUP BY k")
    reg = c._matview_registry
    monkeypatch.setenv("DSQL_MV", "0")
    with pytest.raises(UserError):
        c.sql("CREATE MATERIALIZED VIEW w AS SELECT k FROM t")
    with pytest.raises(UserError):
        c.sql("REFRESH MATERIALIZED VIEW v")
    with pytest.raises(UserError):
        c.sql("DROP MATERIALIZED VIEW v")
    # appends degrade to tombstones while disabled
    c.append_rows("t", [("z", 9.0, 9)])
    assert ("root", "t") not in reg.deltas
    assert reg.tombstones[("root", "t")] > 0


def test_volatile_query_rejected_with_typed_error():
    c = _ctx()
    with pytest.raises(mv.MatViewError) as ei:
        c.sql("CREATE MATERIALIZED VIEW v AS SELECT k, CURRENT_DATE AS d "
              "FROM t")
    assert "volatile" in str(ei.value)
    with pytest.raises(mv.MatViewError):
        c.sql("CREATE MATERIALIZED VIEW v AS SELECT CURRENT_TIME AS ts")
    with pytest.raises(mv.MatViewError):
        c.sql("CREATE MATERIALIZED VIEW v AS SELECT RAND() AS r")
    # nothing half-registered
    assert c.resolve_table(["v"]) is None


def test_duplicate_name_checks():
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT k FROM t")
    with pytest.raises(UserError):
        c.sql("CREATE MATERIALIZED VIEW v AS SELECT x FROM t")
    c.sql("CREATE MATERIALIZED VIEW IF NOT EXISTS v AS SELECT x FROM t")
    c.sql("CREATE OR REPLACE MATERIALIZED VIEW v AS SELECT x FROM t")
    got = c.sql("SELECT * FROM v", return_futures=False)
    assert list(got.columns) == ["x"]
    with pytest.raises(UserError):
        c.sql("DROP MATERIALIZED VIEW nope")
    c.sql("DROP MATERIALIZED VIEW IF EXISTS nope")


# ---------------------------------------------------------------------------
# append_rows coercion
# ---------------------------------------------------------------------------

def test_append_rows_coercion_paths():
    c = _ctx()
    n0 = c.schema["root"].tables["t"].table.num_rows
    # dict of columns, case-insensitive names, any order
    c.append_rows("t", {"Y": [7], "K": ["d"], "X": [4.0]})
    # pandas frame
    c.append_rows("t", pd.DataFrame({"k": ["e"], "x": [5.0], "y": [8]}))
    # list of tuples, positional
    assert c.append_rows("t", [("f", 6.0, 9), ("g", 7.0, 10)]) == 2
    t = c.schema["root"].tables["t"].table
    assert t.num_rows == n0 + 4
    # types still match the original columns
    orig = _ctx().schema["root"].tables["t"].table
    assert [col.stype.name for col in t.columns] == \
        [col.stype.name for col in orig.columns]


def test_append_rows_int_literal_casts_to_double():
    c = _ctx()
    c.append_rows("t", [("h", 8, 11)])  # x is DOUBLE, 8 is int
    t = c.schema["root"].tables["t"].table
    assert t.column("x").stype.name == "DOUBLE"


def test_append_rows_errors_are_typed():
    from dask_sql_tpu.runtime.resilience import SchemaMismatch
    c = _ctx()
    with pytest.raises(UserError):
        c.append_rows("missing", [(1,)])
    with pytest.raises(SchemaMismatch):
        c.append_rows("t", {"k": ["a"], "nope": [1]})  # unknown column
    with pytest.raises(SchemaMismatch):
        c.append_rows("t", [("a", 1.0)])  # arity mismatch
    # a named strict subset NULL-fills the missing columns instead
    c.append_rows("t", {"k": ["sub"]})
    got = c.sql("SELECT x, y FROM t WHERE k = 'sub'", return_futures=False)
    assert got["x"].isna().all() and got["y"].isna().all()
    c.sql("CREATE VIEW lazyv AS SELECT k FROM t")
    with pytest.raises(UserError):
        c.append_rows("lazyv", [("a",)])
    c.sql("CREATE MATERIALIZED VIEW matv AS SELECT k FROM t")
    with pytest.raises(UserError) as ei:
        c.append_rows("matv", [("a",)])
    assert "materialized view" in str(ei.value)


def test_append_rows_chunked_rejected():
    c = Context()
    c.create_table("big", pd.DataFrame({"a": np.arange(100)}),
                   chunked=True, batch_rows=32)
    with pytest.raises(UserError):
        c.append_rows("big", [(1,)])


def test_insert_into_column_list_fills_null():
    c = _ctx()
    c.sql("INSERT INTO t (x, k) VALUES (9.5, 'q')")
    got = c.sql("SELECT y FROM t WHERE k = 'q'", return_futures=False)
    assert got["y"].isna().all()
    with pytest.raises(UserError):
        c.sql("INSERT INTO t (nope) VALUES (1)")
    with pytest.raises(UserError):
        c.sql("INSERT INTO t (k, x) VALUES (1)")  # arity mismatch
