"""Watchtower unit coverage (runtime/events.py): the bounded event bus
and its cursor semantics, trace-ID minting/sanitizing/resolution, the
crash-tolerant JSONL ring, the SLO burn-rate monitor with edge-triggered
breaches, anomaly flags, the system.events/system.slo tables, and the
zero-import disabled path."""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from dask_sql_tpu.runtime import telemetry as tel


@pytest.fixture()
def ev(monkeypatch):
    """Armed watchtower with a fresh bus/monitor per test."""
    monkeypatch.setenv("DSQL_EVENTS", "1")
    from dask_sql_tpu.runtime import events
    events._reset_for_tests()
    yield events
    events._reset_for_tests()


# ---------------------------------------------------------------------------
# the bus
# ---------------------------------------------------------------------------

def test_publish_and_cursor_semantics(ev):
    before = tel.REGISTRY.get("events_published")
    ev.publish("a.one", x=1)
    ev.publish("a.two", x=2)
    ev.publish("a.three", x=3)
    assert tel.REGISTRY.get("events_published") == before + 3
    evs, nxt = ev.read_since(0)
    assert [e["type"] for e in evs] == ["a.one", "a.two", "a.three"]
    assert nxt == evs[-1]["seq"]
    # cursor resumes AFTER what was read
    evs2, nxt2 = ev.read_since(nxt)
    assert evs2 == [] and nxt2 == nxt
    ev.publish("a.four")
    evs3, _ = ev.read_since(nxt)
    assert [e["type"] for e in evs3] == ["a.four"]
    # limit caps the batch, cursor still advances batch-by-batch
    evs4, n4 = ev.read_since(0, limit=2)
    assert len(evs4) == 2 and n4 == evs4[-1]["seq"]


def test_ring_is_bounded(ev, monkeypatch):
    monkeypatch.setenv("DSQL_EVENTS_RING", "16")
    ev._reset_for_tests()  # bus re-reads the ring size
    for i in range(100):
        ev.publish("tick", i=i)
    snap = ev.get_bus().snapshot()
    assert len(snap) == 16
    assert snap[-1]["i"] == 99           # newest survive
    assert snap[0]["i"] == 84            # oldest evicted
    # a cursor older than the ring skips the evicted range cleanly
    evs, _ = ev.read_since(0, limit=1000)
    assert [e["i"] for e in evs] == list(range(84, 100))


def test_long_poll_wakes_on_publish(ev):
    cur = ev.get_bus().last_seq()
    got = []

    def waiter():
        evs, _ = ev.read_since(cur, timeout_s=5.0)
        got.extend(evs)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    ev.publish("wake.up")
    t.join(timeout=5)
    assert not t.is_alive()
    assert [e["type"] for e in got] == ["wake.up"]


def test_publish_never_raises(ev, monkeypatch):
    before = tel.REGISTRY.get("events_dropped")

    def boom(rec):
        raise RuntimeError("bus on fire")

    monkeypatch.setattr(ev.get_bus(), "append", boom)
    assert ev.publish("doomed") is None
    assert tel.REGISTRY.get("events_dropped") == before + 1


def test_core_field_collisions_are_stripped(ev):
    rec = ev.publish("t", seq=999, pid=-1, unix=-1.0, type="fake", x=7)
    assert rec["type"] == "t" and rec["pid"] == os.getpid()
    assert rec["x"] == 7 and rec["seq"] != 999


# ---------------------------------------------------------------------------
# trace IDs
# ---------------------------------------------------------------------------

def test_mint_and_sanitize(ev):
    tid = ev.mint_trace_id()
    assert len(tid) == 16 and ev.sanitize_trace_id(tid) == tid
    assert ev.mint_trace_id() != tid
    assert ev.sanitize_trace_id("abc-DEF_123") == "abc-DEF_123"
    assert ev.sanitize_trace_id("  padded  ") == "padded"  # stripped
    assert ev.sanitize_trace_id("x" * 65) is None
    assert ev.sanitize_trace_id("inj\nected") is None
    assert ev.sanitize_trace_id("semi;colon") is None
    assert ev.sanitize_trace_id("") is None
    assert ev.sanitize_trace_id(None) is None


def test_trace_id_resolution_order(ev, monkeypatch):
    assert ev.current_trace_id() is None
    monkeypatch.setenv("DSQL_TRACE_ID", "from-env")
    assert ev.current_trace_id() == "from-env"
    with ev.trace_id_scope("from-scope"):
        assert ev.current_trace_id() == "from-scope"
        rec = ev.publish("inside")
        assert rec["trace"] == "from-scope"
    assert ev.current_trace_id() == "from-env"
    # invalid env ID resolves to None, not garbage
    monkeypatch.setenv("DSQL_TRACE_ID", "bad id!")
    assert ev.current_trace_id() is None


def test_trace_rides_span_tree_into_report(ev):
    """on_trace_open stamps the root attr; QueryReport picks it up."""
    with tel.trace_scope("SELECT 1") as trace:
        tid = trace.root.attrs.get("trace_id")
        assert tid and ev.sanitize_trace_id(tid) == tid
    report = tel.last_report()
    assert report.trace_id == tid
    assert report.to_dict()["trace_id"] == tid
    # chrome-trace export carries it in the trace-level metadata
    assert report.to_chrome_trace()["otherData"]["trace_id"] == tid
    # ... and the completion landed on the bus with the same ID
    done = [e for e in ev.get_bus().snapshot() if e["type"] == "query.done"]
    assert done and done[-1]["trace"] == tid


# ---------------------------------------------------------------------------
# the JSONL file ring
# ---------------------------------------------------------------------------

def test_file_ring_truncates_at_limit(ev, tmp_path, monkeypatch):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("DSQL_EVENTS_FILE", path)
    monkeypatch.setenv("DSQL_EVENTS_MB", "0.001")  # floor clamps to 4096
    assert ev.file_limit_bytes() == 4096
    pad = "x" * 100
    for i in range(200):
        ev.publish("churn", i=i, pad=pad)
    assert os.path.getsize(path) <= 4096
    recs = ev._read_file(path)
    assert recs and recs[-1]["i"] == 199      # newest kept
    assert recs[0]["i"] > 0                   # oldest dropped


def test_file_ring_skips_corrupt_lines(ev, tmp_path, monkeypatch):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("DSQL_EVENTS_FILE", path)
    ev.publish("good", i=1)
    with open(path, "ab") as f:
        f.write(b"not json\n")
        f.write(b'{"torn": tru')       # torn mid-write
        f.write(b"\n[1, 2, 3]\n")      # json but not a dict
    ev.publish("good", i=2)
    assert [r["i"] for r in ev._read_file(path)] == [1, 2]


def test_events_rows_compacts_extras(ev, tmp_path, monkeypatch):
    ev.publish("shape.test", zeta=1, alpha="two")
    row = ev.events_rows()[-1]
    assert row["type"] == "shape.test"
    assert json.loads(row["detail"]) == {"alpha": "two", "zeta": 1}
    assert set(row) == {"seq", "unix", "pid", "trace", "type", "detail"}


# ---------------------------------------------------------------------------
# SLO monitor
# ---------------------------------------------------------------------------

def test_slo_knob_parsing(ev, monkeypatch):
    assert ev.objective_ms("interactive") == 1000.0
    assert ev.objective_ms("batch") == 10000.0
    assert ev.objective_ms("background") == 60000.0
    monkeypatch.setenv("DSQL_SLO_INTERACTIVE_MS", "250")
    assert ev.objective_ms("interactive") == 250.0
    assert ev.slo_target() == 0.99
    monkeypatch.setenv("DSQL_SLO_TARGET", "2.0")
    assert ev.slo_target() == 0.9999           # clamped
    monkeypatch.setenv("DSQL_SLO_TARGET", "not-a-number")
    assert ev.slo_target() == 0.99


def test_slo_attainment_and_gauges(ev):
    mon = ev.get_monitor()
    mon.observe("interactive", 10.0)           # within 1000ms objective
    mon.observe("interactive", 5000.0)         # breach
    rows = {r["class"]: r for r in ev.slo_rows()}
    r = rows["interactive"]
    assert r["total"] == 2 and r["breaches"] == 1
    assert r["attainment"] == pytest.approx(0.5)
    assert tel.REGISTRY.gauges()["slo_attainment_interactive"] == \
        pytest.approx(0.5)
    # burn = breach_fraction / (1 - target) = 0.5 / 0.01 = 50
    assert r["burn_fast"] == pytest.approx(50.0)
    # untouched classes report clean
    assert rows["batch"]["total"] == 0
    assert rows["batch"]["attainment"] == 1.0


def test_slo_breach_is_edge_triggered(ev):
    before = tel.REGISTRY.get("slo_breaches")
    mon = ev.get_monitor()
    mon.observe("batch", 99999.0)              # 100% breach, burn 100x
    mon.observe("batch", 99999.0)              # still breaching: no re-fire
    mon.observe("batch", 99999.0)
    assert tel.REGISTRY.get("slo_breaches") == before + 1
    breaches = [e for e in ev.get_bus().snapshot()
                if e["type"] == "slo.breach"]
    assert len(breaches) == 1 and breaches[0]["cls"] == "batch"
    assert "batch" in ev.get_monitor().breached_classes()


def test_unknown_priority_maps_to_interactive(ev):
    mon = ev.get_monitor()
    mon.observe(None, 1.0)
    mon.observe("mystery", 1.0)
    rows = {r["class"]: r for r in ev.slo_rows()}
    assert rows["interactive"]["total"] == 2


# ---------------------------------------------------------------------------
# anomaly flags
# ---------------------------------------------------------------------------

def test_compile_error_spike_flag(ev):
    ev._sample_counters(time.time() - 1.0)     # baseline sample
    tel.inc("compile_errors", 5)
    flags = ev.anomalies()
    spike = [f for f in flags if f["kind"] == "compile_error_spike"]
    assert spike and spike[0]["errors"] >= 5


def test_spill_thrash_flag(ev):
    ev._sample_counters(time.time() - 1.0)
    tel.inc("spill_demotions", 40)
    flags = ev.anomalies()
    thrash = [f for f in flags if f["kind"] == "spill_thrash"]
    assert thrash and thrash[0]["moves"] >= 40


def test_engine_section_shape(ev):
    sec = ev.engine_section()
    assert sec["enabled"] is True
    assert {r["class"] for r in sec["classes"]} == \
        {"interactive", "batch", "background"}
    assert isinstance(sec["anomalies"], list)
    assert sec["bus"]["ring"] == ev.ring_len()


# ---------------------------------------------------------------------------
# system tables
# ---------------------------------------------------------------------------

def test_system_events_table_armed(ev, tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_EVENTS_FILE", str(tmp_path / "e.jsonl"))
    with ev.trace_id_scope("tbl-trace"):
        ev.publish("table.test", detail_field=42)
    from dask_sql_tpu.runtime import system_tables as st
    t = st.build("events")
    rows = t.to_pylist()
    by = dict(zip(t.names, rows[-1]))
    assert by["type"] == "table.test" and by["trace"] == "tbl-trace"
    assert json.loads(by["detail"]) == {"detail_field": 42}


def test_system_slo_table_armed(ev):
    ev.get_monitor().observe("interactive", 1.0)
    from dask_sql_tpu.runtime import system_tables as st
    t = st.build("slo")
    assert t.names[0] == "class" and "burn_fast" in t.names
    rows = t.to_pylist()
    assert len(rows) == 3


def test_system_tables_empty_when_disarmed(monkeypatch):
    monkeypatch.delenv("DSQL_EVENTS", raising=False)
    from dask_sql_tpu.runtime import system_tables as st
    for name in ("events", "slo"):
        t = st.build(name)
        assert t.num_rows == 0          # fixed schema, zero rows
        assert t.num_columns > 0


# ---------------------------------------------------------------------------
# the zero-import disabled path
# ---------------------------------------------------------------------------

def test_disabled_query_never_imports_events():
    """With DSQL_EVENTS unset an end-to-end query must leave
    runtime.events out of sys.modules entirely — the tripwire that keeps
    the watchtower's cost at one env read."""
    code = (
        "import sys\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3]})\n"
        "assert c.sql('SELECT SUM(a) AS s FROM t').to_pylist() == [[6]]\n"
        "assert 'dask_sql_tpu.runtime.events' not in sys.modules, \\\n"
        "    'disabled path imported the watchtower'\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()
