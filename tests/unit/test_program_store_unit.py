"""Unit tests: shared kvstore plumbing, the persistent program store's
disk behavior (round-trip, fingerprint rejection, corrupt-entry tolerance,
byte-budget LRU), program-key canonicalization, and compile-worker backoff.
"""
import os
import pickle
import time

import pytest

from dask_sql_tpu.physical import compiled
from dask_sql_tpu.runtime import kvstore as kv
from dask_sql_tpu.runtime import program_store as ps
from dask_sql_tpu.runtime import telemetry as tel


# ---------------------------------------------------------------------------
# kvstore
# ---------------------------------------------------------------------------

def test_kvstore_read_tolerates_missing_and_corrupt(tmp_path):
    path = str(tmp_path / "s.json")
    assert kv.read_json_dict(path) == {}
    with open(path, "w") as f:
        f.write("{not json!")
    assert kv.read_json_dict(path) == {}
    with open(path, "w") as f:
        f.write('{"a": {"x": 1}, "b": 7, "c": [1]}')
    # non-dict values read as absent, dict values survive
    assert kv.read_json_dict(path) == {"a": {"x": 1}}


def test_kvstore_atomic_write_and_digest(tmp_path):
    path = str(tmp_path / "s.json")
    assert kv.atomic_write_json(path, {"k": {"v": 2}})
    assert kv.read_json_dict(path) == {"k": {"v": 2}}
    assert not kv.atomic_write_json(str(tmp_path / "no" / "dir.json"), {})
    assert kv.digest_key(("a", 1)) == kv.digest_key(("a", 1))
    assert kv.digest_key(("a", 1)) != kv.digest_key(("a", 2))


def test_kvstore_mtime_cached_file(tmp_path):
    path = str(tmp_path / "s.json")
    f = kv.MtimeCachedJsonFile(lambda: path)
    assert f.read() == {}
    f.write({"k": {"v": 1}})
    assert f.read() == {"k": {"v": 1}}
    # an external writer's update is observed (mtime invalidation)
    time.sleep(0.01)
    kv.atomic_write_json(path, {"k": {"v": 2}})
    assert f.read() == {"k": {"v": 2}}
    # corrupt file reads as empty, never raises
    with open(path, "w") as fh:
        fh.write("garbage")
    assert f.read() == {}


def test_caps_file_rides_kvstore(tmp_path, monkeypatch):
    path = str(tmp_path / "caps.json")
    monkeypatch.setenv("DSQL_CAPS_FILE", path)
    monkeypatch.setattr(compiled, "_caps_disk", None)
    base_key = ("plan", (("x",),), True)
    compiled._learned_caps_put(base_key, {"agg0": 8192})
    compiled._learned_caps.clear()
    monkeypatch.setattr(compiled, "_caps_disk", None)
    assert compiled._learned_caps_get(base_key) == {"agg0": 8192}


# ---------------------------------------------------------------------------
# program store
# ---------------------------------------------------------------------------

@pytest.fixture()
def store(tmp_path, monkeypatch):
    monkeypatch.setenv("DSQL_PROGRAM_STORE", str(tmp_path / "programs"))
    monkeypatch.delenv("DSQL_PROGRAM_STORE_MB", raising=False)
    return ps.ProgramStore()


def _entry(payload: bytes = b"x" * 64) -> dict:
    return {"v": 1, "caps": {"agg0": 4096}, "spec": [], "meta": {"n_out": 1},
            "payload": payload, "n_args": 2, "n_outs": 3}


def test_store_disabled_without_env(monkeypatch):
    monkeypatch.delenv("DSQL_PROGRAM_STORE", raising=False)
    s = ps.ProgramStore()
    assert not s.enabled()
    assert not s.store("d" * 32, _entry())
    assert s.load("d" * 32) is None


def test_store_round_trip(store):
    d = store.digest(("plan", "inputs", True))
    assert not store.contains(d)
    assert store.store(d, _entry())
    assert store.contains(d)
    got = store.load(d)
    assert got is not None
    assert got["payload"] == b"x" * 64
    assert got["caps"] == {"agg0": 4096}
    assert got["fingerprint"] == ps.runtime_fingerprint()


def test_store_miss_counts(store):
    before = tel.REGISTRY.get("program_store_misses")
    assert store.load(store.digest("never-stored")) is None
    assert tel.REGISTRY.get("program_store_misses") == before + 1


def test_fingerprint_mismatch_rejected(store):
    d = store.digest("some-program")
    store.store(d, _entry())
    # simulate an entry from a different device class / jax version landing
    # at the same digest (hand-copied store, digest collision)
    path = store._entry_path(d)
    with open(path, "rb") as f:
        raw = pickle.load(f)
    raw["fingerprint"] = dict(raw["fingerprint"], device="tpu:v9999:8")
    with open(path, "wb") as f:
        pickle.dump(raw, f)
    before = tel.REGISTRY.get("program_store_rejects")
    assert store.load(d) is None
    assert tel.REGISTRY.get("program_store_rejects") == before + 1


def test_digest_changes_with_runtime_fingerprint(store, monkeypatch):
    key = ("plan", "inputs", True)
    d1 = store.digest(key)
    monkeypatch.setattr(ps, "runtime_fingerprint",
                        lambda: {"device": "other", "jax": "0", "jaxlib": "0",
                                 "format": "1"})
    assert store.digest(key) != d1


def test_corrupt_entry_tolerated_and_dropped(store):
    d = store.digest("will-corrupt")
    store.store(d, _entry())
    with open(store._entry_path(d), "wb") as f:
        f.write(b"\x80truncated-garbage")
    before = tel.REGISTRY.get("program_store_errors")
    assert store.load(d) is None
    assert tel.REGISTRY.get("program_store_errors") == before + 1
    # the broken entry was evicted from disk and index
    assert not os.path.exists(store._entry_path(d))
    assert not store.contains(d)


def test_lru_eviction_at_byte_budget(store, monkeypatch):
    # ~2 KB payloads against a 10 KB budget: the 5th entry must evict the
    # least-recently-USED one, not simply the oldest-stored
    monkeypatch.setenv("DSQL_PROGRAM_STORE_MB", str(10 / 1024.0))
    digests = [store.digest(f"prog{i}") for i in range(5)]
    before = tel.REGISTRY.get("program_store_evictions")
    for i, d in enumerate(digests[:4]):
        assert store.store(d, _entry(payload=b"p" * 2048))
        time.sleep(0.01)
    assert store.total_bytes() <= store.budget_bytes()  # 4 entries fit
    # touch prog0 so prog1 becomes the LRU victim
    assert store.load(digests[0]) is not None
    time.sleep(0.01)
    assert store.store(digests[4], _entry(payload=b"p" * 2048))
    assert tel.REGISTRY.get("program_store_evictions") > before
    assert store.contains(digests[0])
    assert not store.contains(digests[1])
    assert store.contains(digests[4])
    assert store.total_bytes() <= store.budget_bytes()


def test_corrupt_index_tolerated(store):
    d = store.digest("indexed")
    store.store(d, _entry())
    with open(store._index_path(), "w") as f:
        f.write("not json at all")
    # index corruption degrades to "empty index": contains() misses but
    # nothing raises, and a re-store heals it
    assert store.entries() == {}
    assert store.store(d, _entry())
    assert store.contains(d)


# ---------------------------------------------------------------------------
# canonical program key (cross-process stage identity)
# ---------------------------------------------------------------------------

def test_canonical_key_rewrites_boundary_names():
    fp1 = ("Join(T|C=[@0])[s]<Scan(__split__.t0123456789abcdef)[x]<>,"
           "Scan(__split__.tfedcba9876543210)[y]<>>")
    fp2 = ("Join(T|C=[@0])[s]<Scan(__split__.taaaabbbbccccdddd)[x]<>,"
           "Scan(__split__.t1111222233334444)[y]<>>")
    k1 = compiled._canonical_program_key((fp1, "inputs", True))
    k2 = compiled._canonical_program_key((fp2, "inputs", True))
    # different per-process uids, same structure -> same canonical key
    assert k1 == k2
    assert "__split__.#0" in k1[0] and "__split__.#1" in k1[0]
    # REPEATED boundary names must keep their equality structure
    fp3 = ("U<Scan(__split__.t0123456789abcdef)[x]<>,"
           "Scan(__split__.t0123456789abcdef)[x]<>>")
    k3 = compiled._canonical_program_key((fp3, "i", True))
    assert k3[0].count("__split__.#0") == 2
    # base-table scans are untouched
    k4 = compiled._canonical_program_key(("Scan(root.t)[x]", "i", True))
    assert k4[0] == "Scan(root.t)[x]"


# ---------------------------------------------------------------------------
# compile-worker backoff
# ---------------------------------------------------------------------------

@pytest.fixture()
def _clean_streak(monkeypatch):
    monkeypatch.setattr(compiled, "_compile_fail_streak", 0)
    monkeypatch.setenv("DSQL_COMPILE_WORKERS", "4")
    monkeypatch.setenv("DSQL_COMPILE_BACKOFF_AFTER", "2")
    yield
    compiled._compile_fail_streak = 0


def test_compile_backoff_halves_and_recovers(_clean_streak):
    assert compiled._compile_workers() == 4
    before = tel.REGISTRY.get("compile_backoffs")
    compiled._note_compile_result(False)
    assert compiled._compile_workers() == 4  # one failure: not yet
    compiled._note_compile_result(False)
    assert compiled._compile_workers() == 2  # 2 consecutive -> halved
    assert tel.REGISTRY.get("compile_backoffs") == before + 1
    compiled._note_compile_result(False)
    compiled._note_compile_result(False)
    assert compiled._compile_workers() == 1  # 4 consecutive -> quartered
    assert tel.REGISTRY.get("compile_backoffs") == before + 2
    for _ in range(20):
        compiled._note_compile_result(False)
    assert compiled._compile_workers() == 1  # floor of one worker
    compiled._note_compile_result(True)
    assert compiled._compile_workers() == 4  # any success restores


def test_compile_backoff_respects_stage_cap(_clean_streak):
    assert compiled._compile_workers(2) == 2
    compiled._note_compile_result(False)
    compiled._note_compile_result(False)
    assert compiled._compile_workers(8) == 2
    assert compiled._compile_workers(1) == 1
