"""Scatter-free segmented aggregation vs jax.ops.segment_* oracles."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dask_sql_tpu.ops import sorted_agg as sa


def _setup(n=500, g=17, null_frac=0.3, seed=3):
    rng = np.random.RandomState(seed)
    codes = np.sort(rng.randint(0, g, n))
    values = rng.randn(n) * 10
    valid = rng.rand(n) > null_frac
    cs = jnp.asarray(codes)
    starts, ends = sa.segment_bounds(cs, g)
    return (jnp.asarray(values), jnp.asarray(valid), cs, starts, ends,
            codes, values, valid, g)


def test_seg_count_and_sum():
    v, m, cs, starts, ends, codes, values, valid, g = _setup()
    got_c = np.asarray(sa.seg_count(m, starts, ends))
    got_s = np.asarray(sa.seg_sum(v, m, cs, starts, ends))
    for i in range(g):
        sel = (codes == i) & valid
        assert got_c[i] == sel.sum()
        np.testing.assert_allclose(got_s[i], values[sel].sum(), rtol=1e-12)


def test_seg_sum_int():
    codes = jnp.asarray([0, 0, 1, 2, 2, 2])
    vals = jnp.asarray([1, 2, 3, 4, 5, 6], dtype=jnp.int64)
    valid = jnp.asarray([True, True, False, True, True, True])
    starts, ends = sa.segment_bounds(codes, 3)
    got = np.asarray(sa.seg_sum(vals, valid, codes, starts, ends))
    assert got.tolist() == [3, 0, 15]


def test_seg_sum_nonfinite_isolated():
    codes = jnp.asarray([0, 0, 1, 1, 2, 3, 3])
    vals = jnp.asarray([np.nan, 1.0, 2.0, 3.0, np.inf, -np.inf, np.inf])
    valid = jnp.ones(7, bool)
    starts, ends = sa.segment_bounds(codes, 4)
    got = np.asarray(sa.seg_sum(vals, valid, codes, starts, ends))
    assert np.isnan(got[0])
    assert got[1] == 5.0          # NaN in segment 0 must not leak here
    assert got[2] == np.inf
    assert np.isnan(got[3])       # +inf + -inf


def test_seg_min_max():
    v, m, cs, starts, ends, codes, values, valid, g = _setup(seed=5)
    got_min = np.asarray(sa.seg_min(v, m, cs, ends))
    got_max = np.asarray(sa.seg_max(v, m, cs, ends))
    for i in range(g):
        sel = (codes == i) & valid
        if sel.any():
            assert got_min[i] == values[sel].min()
            assert got_max[i] == values[sel].max()


def test_first_last_valid_pos():
    codes = jnp.asarray([0, 0, 0, 1, 1, 2])
    valid = jnp.asarray([False, True, True, False, False, True])
    starts, ends = sa.segment_bounds(codes, 3)
    first = np.asarray(sa.seg_first_valid_pos(valid, codes, ends))
    last = np.asarray(sa.seg_last_valid_pos(valid, codes, ends))
    assert first.tolist() == [1, 6, 5]   # segment 1 has no valid row -> n
    assert last.tolist() == [2, -1, 5]


def test_empty_trailing_segments():
    codes = jnp.asarray([0, 0, 1])
    vals = jnp.asarray([1.0, 2.0, 3.0])
    valid = jnp.ones(3, bool)
    starts, ends = sa.segment_bounds(codes, 5)
    got = np.asarray(sa.seg_sum(vals, valid, codes, starts, ends))
    assert got.tolist() == [3.0, 3.0, 0.0, 0.0, 0.0]


def test_seg_sum_no_cross_group_cancellation():
    """A huge-magnitude group must not destroy later groups' precision (a
    global prefix sum would absorb small values into the big running total)."""
    codes = jnp.asarray([0, 1, 1, 1, 1])
    vals = jnp.asarray([1e18, 1.0, 1.0, 1.0, 1.0])
    valid = jnp.ones(5, bool)
    starts, ends = sa.segment_bounds(codes, 2)
    got = np.asarray(sa.seg_sum(vals, valid, codes, starts, ends))
    assert got[0] == 1e18
    assert got[1] == 4.0
