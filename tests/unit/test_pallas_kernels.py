"""Pallas segmented-reduction kernel vs the XLA scatter oracle.

Runs in interpreter mode on the CPU test mesh; the same code path compiles
natively on TPU.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from dask_sql_tpu.ops import pallas_kernels as pk


@pytest.mark.parametrize("n,g,a", [(100, 3, 1), (1024, 8, 4), (5000, 60, 2)])
def test_segmented_sums_matches_oracle(n, g, a):
    rng = np.random.RandomState(7)
    vals = jnp.asarray(rng.randn(a, n))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = pk.segmented_sums(vals, codes, mask, g, interpret=True)
    want = pk.reference_segmented_sums(vals, codes, mask, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-10)


def test_all_masked_rows_are_zero():
    vals = jnp.ones((2, 300))
    codes = jnp.zeros(300, dtype=jnp.int32)
    mask = jnp.zeros(300, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 4, interpret=True)
    assert np.allclose(np.asarray(got), 0.0)


def test_padding_rows_do_not_leak():
    # n not a multiple of BLOCK: padded tail must not contribute to group 0
    n = pk.BLOCK + 17
    vals = jnp.ones((1, n))
    codes = jnp.zeros(n, dtype=jnp.int32)
    mask = jnp.ones(n, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 2, interpret=True)
    assert got[0, 0] == n
    assert got[0, 1] == 0
