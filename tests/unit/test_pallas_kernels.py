"""Pallas segmented-reduction kernel vs the XLA scatter oracle.

Runs in interpreter mode on the CPU test mesh; the same code path compiles
natively on TPU.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from dask_sql_tpu.ops import pallas_kernels as pk


@pytest.mark.parametrize("n,g,a", [(100, 3, 1), (1024, 8, 4), (5000, 60, 2)])
def test_segmented_sums_matches_oracle(n, g, a):
    rng = np.random.RandomState(7)
    vals = jnp.asarray(rng.randn(a, n))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = pk.segmented_sums(vals, codes, mask, g, interpret=True)
    want = pk.reference_segmented_sums(vals, codes, mask, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-10)


def test_all_masked_rows_are_zero():
    vals = jnp.ones((2, 300))
    codes = jnp.zeros(300, dtype=jnp.int32)
    mask = jnp.zeros(300, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 4, interpret=True)
    assert np.allclose(np.asarray(got), 0.0)


def test_padding_rows_do_not_leak():
    # n not a multiple of BLOCK: padded tail must not contribute to group 0
    n = pk.BLOCK + 17
    vals = jnp.ones((1, n))
    codes = jnp.zeros(n, dtype=jnp.int32)
    mask = jnp.ones(n, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 2, interpret=True)
    assert got[0, 0] == n
    assert got[0, 1] == 0


def test_nan_inf_isolated_to_their_groups():
    """NaN/Inf values must only affect their own group (NaN*0 == NaN would
    otherwise poison every group through the one-hot contraction)."""
    vals = jnp.asarray([[np.nan, 1.0, 2.0, 3.0, np.inf, -np.inf, 5.0, 6.0]])
    codes = jnp.asarray([0, 1, 1, 1, 2, 3, 4, 4])
    mask = jnp.ones(8, dtype=bool)
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 5, interpret=True))
    assert np.isnan(got[0, 0])
    assert got[0, 1] == 6.0
    assert got[0, 2] == np.inf
    assert got[0, 3] == -np.inf
    assert got[0, 4] == 11.0


def test_masked_nan_contributes_nothing():
    vals = jnp.asarray([[np.nan, 1.0, 2.0]])
    codes = jnp.asarray([0, 0, 1])
    mask = jnp.asarray([False, True, True])
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 2, interpret=True))
    assert got[0, 0] == 1.0 and got[0, 1] == 2.0


def test_posneg_inf_same_group_is_nan():
    vals = jnp.asarray([[np.inf, -np.inf, 1.0]])
    codes = jnp.asarray([0, 0, 1])
    mask = jnp.ones(3, dtype=bool)
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 2, interpret=True))
    assert np.isnan(got[0, 0]) and got[0, 1] == 1.0


def test_xla_blocked_matches_oracle():
    rng = np.random.RandomState(11)
    n, g, a = 5000, 60, 3
    vals = jnp.asarray(rng.randn(a, n))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = pk.segmented_sums_xla_blocked(vals, codes, mask, g, block=512)
    want = pk.reference_segmented_sums(vals, codes, mask, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-10)


def test_xla_blocked_nonfinite_safe_wrapper():
    vals = jnp.asarray([[np.nan, 1.0, 2.0, np.inf]])
    codes = jnp.asarray([0, 1, 1, 2])
    mask = jnp.ones(4, dtype=bool)
    got = np.asarray(pk._nonfinite_safe(pk.segmented_sums_xla_blocked)(
        vals, codes, mask, 3))
    assert np.isnan(got[0, 0]) and got[0, 1] == 3.0 and got[0, 2] == np.inf


@pytest.mark.parametrize("n,g,a", [(100, 3, 1), (5000, 25, 3), (9000, 8, 2)])
def test_segmented_sums_exact_matches_oracle_bitwise(n, g, a):
    """The limb kernel's claim is EXACTNESS on integer-grid values (scaled
    decimals / counts), including negatives and magnitudes near 2**52."""
    rng = np.random.RandomState(11)
    # integer grid up to ~1e9 per value plus a few +-2**50 outliers: total
    # magnitude stays inside the kernel's sum(|v|) < 2**53 contract (the
    # same bound the f64 scan path it replaces had)
    vals = rng.randint(-10**9, 10**9, (a, n)).astype(np.float64)
    vals[:, 0] = 2.0**50
    vals[:, 1] = -(2.0**50)
    vals[:, 2] = 2.0**50
    vals = jnp.asarray(vals)
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = np.asarray(pk.segmented_sums_exact(vals, codes, mask, g,
                                             interpret=True))
    # numpy int64 accumulation is the exact oracle
    vn = np.asarray(vals).astype(np.int64)
    cn, mn = np.asarray(codes), np.asarray(mask)
    want = np.zeros((a, g), dtype=np.int64)
    for gg in range(g):
        want[:, gg] = vn[:, mn & (cn == gg)].sum(axis=1)
    assert np.array_equal(got, want.astype(np.float64)), (
        np.abs(got - want).max())


def test_segmented_sums_exact_nonfinite_masked_rows_ignored():
    vals = jnp.asarray([[1.0, np.nan, 3.0, np.inf, 5.0]])
    codes = jnp.asarray([0, 0, 1, 1, 1])
    mask = jnp.asarray([True, False, True, False, True])
    got = np.asarray(pk.segmented_sums_exact(vals, codes, mask, 2,
                                             interpret=True))
    assert np.array_equal(got, np.asarray([[1.0, 8.0]]))


def test_segmented_sums_exact_nonfinite_poison_confined():
    vals = jnp.asarray([[1.0, np.inf, 2.0, 4.0]])
    codes = jnp.asarray([0, 0, 1, 1])
    mask = jnp.ones(4, dtype=bool)
    got = np.asarray(pk.segmented_sums_exact(vals, codes, mask, 2,
                                             interpret=True))
    assert np.isposinf(got[0, 0]) and got[0, 1] == 6.0


def test_dispatch_mixed_classes_matches_oracle(monkeypatch):
    """Mixed int/float/unit stacks ride one limb kernel call; int rows stay
    bit-exact, float rows land within sub-ulp of the f64 oracle."""
    monkeypatch.setenv("DSQL_PALLAS", "force")
    rng = np.random.RandomState(3)
    n, g = 2048, 6
    vals = jnp.asarray(np.vstack([
        np.round(rng.randint(-10**9, 10**9, n)).astype(np.float64),
        rng.randn(n),
        (rng.rand(n) > 0.5).astype(np.float64),
    ]))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.2)
    got = np.asarray(pk.segmented_sums_dispatch(
        vals, codes, mask, g, row_classes=["int", "float", "unit"]))
    want = np.asarray(pk.reference_segmented_sums(vals, codes, mask, g))
    assert np.array_equal(got[0], want[0])      # int row: bit-exact
    assert np.array_equal(got[2], want[2])      # unit row: bit-exact
    np.testing.assert_allclose(got[1], want[1], rtol=1e-12)


def test_fixedpoint_float_rows_beat_f64_accumulation():
    """Float rows: the fixed-point sum is within one ulp-of-max of the
    TRUE sum (np.float128 oracle) across 12 orders of magnitude — tighter
    than f64 accumulation, which the old scan path could only match."""
    rng = np.random.RandomState(7)
    n, g = 20000, 4
    vals = (rng.randn(2, n) * 10.0 ** rng.randint(-6, 7, (2, n))
            ).astype(np.float64)
    codes = rng.randint(0, g, n)
    mask = rng.rand(n) > 0.1
    got = np.asarray(pk.segmented_sums_fixedpoint(
        jnp.asarray(vals), jnp.asarray(codes), jnp.asarray(mask), g,
        row_classes=["float", "float"], interpret=True))
    for i in range(2):
        for gg in range(g):
            sel = mask & (codes == gg)
            want = vals[i, sel].astype(np.float128).sum()
            # ~1 ulp of the sum (compensated recombination) + the grid
            # truncation bound n * max|v| * 2**-81
            tol = (2.0 * abs(float(want)) * 2.0 ** -52
                   + sel.sum() * np.abs(vals[i, sel]).max(initial=0.0)
                   * 2.0 ** -81)
            assert abs(float(want) - got[i, gg]) <= max(tol, 1e-300), (
                i, gg, float(want), got[i, gg])


def test_fixedpoint_tiny_and_huge_magnitudes():
    """Runtime power-of-two normalization handles extreme row scales."""
    for m in (1e-200, 1.0, 1e200):
        vals = jnp.asarray([[m, 2 * m, -m, 3 * m]])
        codes = jnp.asarray([0, 0, 1, 1])
        mask = jnp.ones(4, bool)
        got = np.asarray(pk.segmented_sums_fixedpoint(
            vals, codes, mask, 2, row_classes=["float"], interpret=True))
        np.testing.assert_allclose(got, [[3 * m, 2 * m]], rtol=1e-12)


def test_fixedpoint_zero_row_and_empty_input():
    got = np.asarray(pk.segmented_sums_fixedpoint(
        jnp.zeros((2, 5)), jnp.zeros(5, jnp.int32), jnp.ones(5, bool), 3,
        row_classes=["float", "int"], interpret=True))
    assert np.array_equal(got, np.zeros((2, 3)))
    got = np.asarray(pk.segmented_sums_fixedpoint(
        jnp.zeros((2, 0)), jnp.zeros(0, jnp.int32), jnp.ones(0, bool), 3,
        row_classes=["float", "int"], interpret=True))
    assert np.array_equal(got, np.zeros((2, 3)))


# ---------------------------------------------------------------------------
# TPU-compilability regression (ADVICE r5 high): the f64 fixed-point path
# must not trace frexp/ldexp — they lower to an s64 bitcast-convert the TPU
# X64 rewrite does not implement, which silently exiled every f64
# static-domain aggregate (the Q1 path) to eager.  The CPU-lowered HLO is
# scanned as a proxy: the banned lowering appears on every backend.
# ---------------------------------------------------------------------------

def test_exact_pow2_is_exact_over_full_range():
    n = np.arange(-1000, 1013)
    got = np.asarray(pk._exact_pow2(jnp.asarray(n, dtype=jnp.int32)))
    want = np.ldexp(np.ones(len(n)), n)
    assert (got == want).all()


def test_dispatch_compile_smoke_no_64bit_bitcast():
    """segmented_sums_dispatch's f64 fixed-point route must lower without
    any 64-bit bitcast-convert (frexp/ldexp would introduce one)."""
    import os

    import jax

    rng = np.random.RandomState(3)
    vals = jnp.asarray(np.stack([
        (rng.rand(512) > 0.5).astype(np.float64),        # 'unit': 0/1
        rng.randint(-10**9, 10**9, 512).astype(np.float64),  # 'int'
        rng.randn(512) * 1e5,                            # 'float'
    ]))
    codes = jnp.asarray(rng.randint(0, 5, 512))
    mask = jnp.asarray(rng.rand(512) > 0.2)
    os.environ["DSQL_PALLAS"] = "force"
    try:
        fn = lambda v, c, m: pk.segmented_sums_dispatch(  # noqa: E731
            v, c, m, 5, row_classes=["unit", "int", "float"])
        lowered = jax.jit(fn).lower(vals, codes, mask)
        text = lowered.as_text()
        assert "bitcast_convert" not in text, (
            "64-bit bitcast-convert in the lowered module — the TPU X64 "
            "rewrite cannot compile it")
        # and it actually compiles + matches the oracle on this backend
        got = np.asarray(jax.jit(fn)(vals, codes, mask))
        want = np.asarray(pk.reference_segmented_sums(vals, codes, mask, 5))
        np.testing.assert_allclose(got, want, rtol=1e-9)
    finally:
        del os.environ["DSQL_PALLAS"]


def test_fixedpoint_masked_outlier_does_not_coarsen_grid():
    """ADVICE r5 medium: absmax must cover mask-CONTRIBUTING values only —
    a filtered-out 1e300 row must not zero the valid sums."""
    vals = jnp.asarray([[1.0, 2.0, 1e300, 3.0]])
    codes = jnp.asarray([0, 0, 1, 1])
    mask = jnp.asarray([True, True, False, True])
    got = np.asarray(pk.segmented_sums_fixedpoint(
        vals, codes, mask, 2, row_classes=["float"], interpret=True))
    np.testing.assert_allclose(got, [[3.0, 3.0]], rtol=1e-12)
