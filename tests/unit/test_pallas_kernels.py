"""Pallas segmented-reduction kernel vs the XLA scatter oracle.

Runs in interpreter mode on the CPU test mesh; the same code path compiles
natively on TPU.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from dask_sql_tpu.ops import pallas_kernels as pk


@pytest.mark.parametrize("n,g,a", [(100, 3, 1), (1024, 8, 4), (5000, 60, 2)])
def test_segmented_sums_matches_oracle(n, g, a):
    rng = np.random.RandomState(7)
    vals = jnp.asarray(rng.randn(a, n))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = pk.segmented_sums(vals, codes, mask, g, interpret=True)
    want = pk.reference_segmented_sums(vals, codes, mask, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-10)


def test_all_masked_rows_are_zero():
    vals = jnp.ones((2, 300))
    codes = jnp.zeros(300, dtype=jnp.int32)
    mask = jnp.zeros(300, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 4, interpret=True)
    assert np.allclose(np.asarray(got), 0.0)


def test_padding_rows_do_not_leak():
    # n not a multiple of BLOCK: padded tail must not contribute to group 0
    n = pk.BLOCK + 17
    vals = jnp.ones((1, n))
    codes = jnp.zeros(n, dtype=jnp.int32)
    mask = jnp.ones(n, dtype=bool)
    got = pk.segmented_sums(vals, codes, mask, 2, interpret=True)
    assert got[0, 0] == n
    assert got[0, 1] == 0


def test_nan_inf_isolated_to_their_groups():
    """NaN/Inf values must only affect their own group (NaN*0 == NaN would
    otherwise poison every group through the one-hot contraction)."""
    vals = jnp.asarray([[np.nan, 1.0, 2.0, 3.0, np.inf, -np.inf, 5.0, 6.0]])
    codes = jnp.asarray([0, 1, 1, 1, 2, 3, 4, 4])
    mask = jnp.ones(8, dtype=bool)
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 5, interpret=True))
    assert np.isnan(got[0, 0])
    assert got[0, 1] == 6.0
    assert got[0, 2] == np.inf
    assert got[0, 3] == -np.inf
    assert got[0, 4] == 11.0


def test_masked_nan_contributes_nothing():
    vals = jnp.asarray([[np.nan, 1.0, 2.0]])
    codes = jnp.asarray([0, 0, 1])
    mask = jnp.asarray([False, True, True])
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 2, interpret=True))
    assert got[0, 0] == 1.0 and got[0, 1] == 2.0


def test_posneg_inf_same_group_is_nan():
    vals = jnp.asarray([[np.inf, -np.inf, 1.0]])
    codes = jnp.asarray([0, 0, 1])
    mask = jnp.ones(3, dtype=bool)
    got = np.asarray(pk.segmented_sums(vals, codes, mask, 2, interpret=True))
    assert np.isnan(got[0, 0]) and got[0, 1] == 1.0


def test_xla_blocked_matches_oracle():
    rng = np.random.RandomState(11)
    n, g, a = 5000, 60, 3
    vals = jnp.asarray(rng.randn(a, n))
    codes = jnp.asarray(rng.randint(0, g, n))
    mask = jnp.asarray(rng.rand(n) > 0.3)
    got = pk.segmented_sums_xla_blocked(vals, codes, mask, g, block=512)
    want = pk.reference_segmented_sums(vals, codes, mask, g)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-10)


def test_xla_blocked_nonfinite_safe_wrapper():
    vals = jnp.asarray([[np.nan, 1.0, 2.0, np.inf]])
    codes = jnp.asarray([0, 1, 1, 2])
    mask = jnp.ones(4, dtype=bool)
    got = np.asarray(pk._nonfinite_safe(pk.segmented_sums_xla_blocked)(
        vals, codes, mask, 3))
    assert np.isnan(got[0, 0]) and got[0, 1] == 3.0 and got[0, 2] == np.inf
