"""The audited error -> wire mapping (server/app.py ERROR_WIRE_MATRIX):
every ResilienceError subclass must yield a STABLE submit-time HTTP
status, errorType and errorName — clients and load balancers key retry
policy on these, so a drifting name is a breaking change exactly like a
renamed metric."""
import pytest

from dask_sql_tpu.runtime import faults as F
from dask_sql_tpu.runtime import resilience as R
from dask_sql_tpu.server import app


def _instance(name: str):
    if name == "FaultInjected":
        return F.FaultInjected("compile", 1)
    if name == "FatalFaultInjected":
        return F.FatalFaultInjected("compile", 1)
    if name.startswith("Spill"):
        from dask_sql_tpu.runtime import spill as S
        return getattr(S, name)("boom")
    return getattr(R, name)("boom")


@pytest.mark.parametrize("name,expected",
                         sorted(app.ERROR_WIRE_MATRIX.items()))
def test_wire_matrix_row(name, expected):
    status, error_type, error_name = expected
    exc = _instance(name)
    assert app.submit_status(exc) == status
    payload = app._error_payload(str(exc), "uid-1", exc=exc)
    err = payload["error"]
    assert err["errorType"] == error_type, name
    assert err["errorName"] == error_name, name
    assert err["errorCode"] == exc.error_code, name
    assert payload["stats"]["state"] == "FAILED"


def test_matrix_covers_every_taxonomy_class():
    """A NEW ResilienceError subclass must either join the audited matrix
    or inherit a mapped ancestor's wire identity UNCHANGED (e.g.
    streaming's StreamingUnsupported is a plain UserError on the wire) —
    silently drifting errorType/errorName is a breaking change."""
    mapped = set(app.ERROR_WIRE_MATRIX)
    for cls in _walk(R.ResilienceError):
        if cls is R.ResilienceError or cls.__name__ in mapped:
            continue
        anc = next((a for a in cls.__mro__[1:] if a.__name__ in mapped),
                   None)
        assert anc is not None, f"unmapped taxonomy class {cls.__name__}"
        for attr in ("error_type", "error_name", "error_code"):
            assert getattr(cls, attr) == getattr(anc, attr), (
                f"{cls.__name__} overrides {attr} but is not in "
                f"ERROR_WIRE_MATRIX")


def _walk(cls):
    yield cls
    for sub in cls.__subclasses__():
        yield from _walk(sub)


def test_oom_transient_keeps_memory_limit_name():
    """TransientError(kind='oom') is the one taxonomy member whose wire
    identity depends on a constructor argument; pin it separately."""
    exc = R.TransientError("oom", kind="oom")
    err = app._error_payload("x", "u", exc=exc)["error"]
    assert err["errorType"] == "INSUFFICIENT_RESOURCES"
    assert err["errorName"] == "EXCEEDED_MEMORY_LIMIT"
    assert app.submit_status(exc) == 200


def test_retry_after_header_sources():
    """429/503 verdicts carry a usable Retry-After seed."""
    assert R.AdmissionRejected("x", retry_after_s=2.5).retry_after_s == 2.5
    assert R.ServerDraining("x", retry_after_s=30).retry_after_s == 30
    # ServerDraining is an AdmissionRejected: anything handling the 429
    # family (seat release, retry hints) handles draining for free
    assert issubclass(R.ServerDraining, R.AdmissionRejected)


@pytest.mark.parametrize("cls_name,error_name", [
    ("TenantQuotaExceeded", "TENANT_QUOTA_EXCEEDED"),
    ("TenantCircuitOpen", "TENANT_CIRCUIT_OPEN"),
    ("LoadShedRejected", "SLO_LOAD_SHED"),
])
def test_new_429_family_rides_admission_rejected(cls_name, error_name):
    """ISSUE 17's tenant-quota / circuit-breaker / load-shed verdicts are
    AdmissionRejected subclasses: the whole 429 + Retry-After wire path
    (submit_status, the server's reject closure, seat/grant release)
    handles them with zero new plumbing — and each keeps its own audited
    errorName so clients can key DISTINCT retry policy on them."""
    cls = getattr(R, cls_name)
    exc = cls("boom", retry_after_s=3.25)
    assert isinstance(exc, R.AdmissionRejected)
    assert app.submit_status(exc) == 429
    assert exc.retry_after_s == 3.25
    err = app._error_payload("boom", "uid-1", exc=exc)["error"]
    assert err["errorType"] == "INSUFFICIENT_RESOURCES"
    assert err["errorName"] == error_name
    # classify() must pass the typed verdict through unchanged — a
    # re-wrap would demote it to the parent's QUERY_QUEUE_FULL name
    assert R.classify(exc) is exc


def test_tenant_reject_wire_handshake_with_trace(monkeypatch):
    """Wire-level: a tenant-quota 429 from a REAL server carries an
    honest Retry-After header AND the X-DSQL-Trace correlation header
    when the watchtower is armed (the reject closure merges both)."""
    import json
    import urllib.error
    import urllib.request

    import pandas as pd

    monkeypatch.setenv("DSQL_EVENTS", "1")
    monkeypatch.setenv("DSQL_TENANT_CONCURRENT", "1")
    from dask_sql_tpu.context import Context
    from dask_sql_tpu.runtime import tenancy
    from dask_sql_tpu.server.app import run_server

    tenancy.get_registry()._reset_for_tests()
    context = Context()
    context.create_table("df", pd.DataFrame({"a": [1, 2, 3]}))
    srv = run_server(context=context, host="127.0.0.1", port=0,
                     blocking=False)
    try:
        base = f"http://127.0.0.1:{srv.server_port}"
        # hold the single concurrency slot open by claiming it directly
        grant = tenancy.get_registry().claim("crowded")
        req = urllib.request.Request(
            f"{base}/v1/statement", data=b"SELECT 1 + 1", method="POST",
            headers={"X-DSQL-Tenant": "crowded",
                     "X-DSQL-Trace": "trace-xyz"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert ei.value.headers["X-DSQL-Trace"] == "trace-xyz"
        err = json.loads(ei.value.read())["error"]
        assert err["errorName"] == "TENANT_QUOTA_EXCEEDED"
        assert err["errorType"] == "INSUFFICIENT_RESOURCES"
        tenancy.get_registry().release(grant)
    finally:
        srv.shutdown()
        tenancy.get_registry()._reset_for_tests()
