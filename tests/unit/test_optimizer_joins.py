"""Join-reorder unit tests: the rewrite must fire only when the ORIGINAL
tree genuinely contains a stranded (cross) step.

The reference's HepPlanner never reorders connected trees either (its
JoinCommuteRule/JoinAssociateRule set is not enabled in dask-sql's default
program); reorder_joins exists to rescue comma-FROM queries whose textual
order strands a leaf, and must leave connected plans — including BUSHY
ones — exactly as written (ADVICE r1 finding 2).
"""
from dask_sql_tpu.plan.nodes import (
    Field, LogicalJoin, LogicalTableScan, RexCall, RexInputRef,
)
from dask_sql_tpu.plan.optimizer import reorder_joins
from dask_sql_tpu.types import BIGINT, BOOLEAN


def _scan(table, *cols):
    return LogicalTableScan(schema_name="root", table_name=table,
                            schema=[Field(c, BIGINT) for c in cols])


def _eq(i, j):
    return RexCall(op="=", operands=[RexInputRef(i, BIGINT),
                                     RexInputRef(j, BIGINT)],
                   stype=BOOLEAN)


def test_connected_bushy_tree_not_rewritten():
    """A ⋈ (B ⋈ C on b=c) on a=c is fully connected; linearizing its leaf
    list as a left-deep chain would falsely count B as stranded (b=c needs C
    which 'hasn't joined yet') and rewrite a plan that needs no help."""
    a, b, c = _scan("a", "a1"), _scan("b", "b1"), _scan("c", "c1")
    inner = LogicalJoin(left=b, right=c, join_type="INNER",
                        condition=_eq(0, 1),
                        schema=list(b.schema) + list(c.schema))
    root = LogicalJoin(left=a, right=inner, join_type="INNER",
                       condition=_eq(0, 2),
                       schema=list(a.schema) + list(inner.schema))
    out = reorder_joins(root)
    assert out == root  # structurally untouched: still bushy, same conds


def test_stranded_chain_still_rewritten():
    """(A ⋈ B cross) ⋈ C with conditions a=c and b=c at the top is the
    comma-FROM shape the rewrite exists for: the textual order strands B."""
    a, b, c = _scan("a", "a1"), _scan("b", "b1"), _scan("c", "c1")
    cross = LogicalJoin(left=a, right=b, join_type="CROSS", condition=None,
                        schema=list(a.schema) + list(b.schema))
    cond = RexCall(op="AND", operands=[_eq(0, 2), _eq(1, 2)], stype=BOOLEAN)
    root = LogicalJoin(left=cross, right=c, join_type="INNER", condition=cond,
                       schema=list(cross.schema) + list(c.schema))
    out = reorder_joins(root)
    assert out is not root

    def no_cross(rel):
        if isinstance(rel, LogicalJoin):
            assert rel.join_type != "CROSS" and rel.condition is not None
            for i in rel.inputs:
                no_cross(i)

    # the rewrite's entire purpose: no stranded steps remain
    while not isinstance(out, LogicalJoin):
        out = out.inputs[0]
    no_cross(out)
