"""Unit: parameterized plan identity (plan/parameterize.py, ISSUE 16).

Pins the hoisting eligibility rules, idempotence, the DSQL_PARAM_PLANS
kill switch, fingerprint behavior (one program identity across literal
variants of a shape; distinct identities with the switch off), and the
result-cache canonicalization contract: RexParam is value-bearing by
default (result keys must distinguish literals) and slot+type in shape
mode (EWMA history must not).  Also audits _canon_rel literal coverage:
VALUES rows and scalar-subquery bodies participate in canonicalization,
and volatile expressions are never hoisted.
"""
import os

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.plan import nodes as N
from dask_sql_tpu.plan.parameterize import (
    collect_params, param_plans_enabled, parameterize_plan)
from dask_sql_tpu.runtime import result_cache as rc
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture()
def ctx():
    c = Context()
    c.create_table("t", pd.DataFrame({
        "a": range(20), "b": [float(i) * 0.5 for i in range(20)],
        "s": [f"v{i % 3}" for i in range(20)]}))
    return c


def _plan(ctx, sql):
    return ctx._get_plan(parse_sql(sql)[0].query, sql)


def _rex_kinds(plan):
    """Flatten every expression node class name in the plan (recursive)."""
    out = []

    def rex(r):
        out.append(type(r).__name__)
        if isinstance(r, (N.RexCall, N.RexUdf)):
            for o in r.operands:
                rex(o)
        elif isinstance(r, N.RexScalarSubquery):
            rel(r.plan)

    def rel(node):
        if isinstance(node, N.LogicalProject):
            for e in node.exprs:
                rex(e)
        elif isinstance(node, N.LogicalFilter):
            rex(node.condition)
        elif isinstance(node, N.LogicalJoin) and node.condition is not None:
            rex(node.condition)
        for k in node.inputs:
            rel(k)

    rel(plan)
    return out


# ---------------------------------------------------------------------------
# hoisting eligibility
# ---------------------------------------------------------------------------

def test_comparison_literals_hoist(ctx):
    plan = _plan(ctx, "SELECT a FROM t WHERE a > 5 AND b <= 7.5")
    new, n = parameterize_plan(plan)
    assert n == 2
    params = collect_params(new)
    assert [p.value for p in params] == [5, 7.5]
    assert [p.slot for p in params] == [0, 1]
    # original plan untouched (the pass copies rewritten nodes)
    assert collect_params(plan) == []


def test_string_bool_null_literals_stay_baked(ctx):
    # strings resolve to dictionary codes at trace time; bools/NULLs steer
    # trace-time simplification — none may become runtime arguments
    plan = _plan(ctx, "SELECT a FROM t WHERE s = 'v1'")
    _, n = parameterize_plan(plan)
    assert n == 0
    plan = _plan(ctx, "SELECT a FROM t WHERE (a > 3) = TRUE")
    new, _ = parameterize_plan(plan)
    assert all(not (isinstance(p, N.RexParam)
                    and isinstance(p.value, bool))
               for p in collect_params(new))


def test_both_scalar_comparison_not_hoisted(ctx):
    # 1 < 2 has no column ref on either side: hoisting would push a traced
    # scalar through the host `bool()` branch of ops.comparison
    plan = _plan(ctx, "SELECT a FROM t WHERE 1 < 2 AND a > 5")
    new, n = parameterize_plan(plan)
    assert n == 1
    assert [p.value for p in collect_params(new)] == [5]


def test_in_list_arity_stays_structural(ctx):
    # IN lowers to OR-of-equals or a structural op; its arity is program
    # STRUCTURE.  Equality arms that lower to plain `a = k` comparisons
    # may hoist — what must hold is that two IN lists of different LENGTH
    # never share a fingerprint (checked below via canonical text).
    p2 = _plan(ctx, "SELECT a FROM t WHERE a IN (1, 2)")
    p3 = _plan(ctx, "SELECT a FROM t WHERE a IN (1, 2, 3)")
    n2, _ = parameterize_plan(p2)
    n3, _ = parameterize_plan(p3)
    t2 = rc.canonical_plan(n2, ctx, shape=True)[0]
    t3 = rc.canonical_plan(n3, ctx, shape=True)[0]
    assert t2 != t3


def test_volatile_expressions_never_hoisted(ctx):
    plan = _plan(ctx, "SELECT a FROM t WHERE b > RAND(1) AND RAND(2) < 0.5")
    new, n = parameterize_plan(plan)
    assert n == 0
    assert collect_params(new) == []


def test_values_rows_stay_baked(ctx):
    plan = _plan(ctx, "SELECT * FROM (VALUES (1, 2.0), (3, 4.0)) AS v(x, y)")
    new, n = parameterize_plan(plan)
    assert n == 0
    # and VALUES literals participate in canonicalization: different rows,
    # different canonical text (the result cache must not cross-serve)
    other = _plan(ctx, "SELECT * FROM (VALUES (9, 2.0), (3, 4.0)) AS v(x, y)")
    assert (rc.canonical_plan(new, ctx)[0]
            != rc.canonical_plan(other, ctx)[0])


def test_scalar_subquery_body_stays_baked_but_canonicalized(ctx):
    q = "SELECT a FROM t WHERE b > (SELECT AVG(b) FROM t WHERE a > {k})"
    p5 = _plan(ctx, q.format(k=5))
    p9 = _plan(ctx, q.format(k=9))
    n5, h5 = parameterize_plan(p5)
    parameterize_plan(p9)
    # the subquery body is specialized wholesale: no param inside it
    sub_lits = [k for k in _rex_kinds(n5) if k == "RexParam"]
    assert len(sub_lits) == h5  # only the outer hoists (if any)
    # ... and its literal is visible to the canonicalizer
    assert rc.canonical_plan(p5, ctx)[0] != rc.canonical_plan(p9, ctx)[0]


def test_idempotent(ctx):
    plan = _plan(ctx, "SELECT a FROM t WHERE a > 5")
    once, n1 = parameterize_plan(plan)
    twice, n2 = parameterize_plan(once)
    assert n1 == 1 and n2 == 0
    assert twice is once


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("DSQL_PARAM_PLANS", "0")
    assert not param_plans_enabled()
    monkeypatch.setenv("DSQL_PARAM_PLANS", "1")
    assert param_plans_enabled()
    monkeypatch.delenv("DSQL_PARAM_PLANS")
    assert param_plans_enabled()


# ---------------------------------------------------------------------------
# fingerprint identity (physical/compiled._fp_plan)
# ---------------------------------------------------------------------------

def _fp(ctx, plan):
    from dask_sql_tpu.physical.compiled import _fp_plan
    params = []
    return _fp_plan(plan, ctx, [], params), params


def test_shape_fingerprint_shared_across_literals(ctx):
    a = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 5"))[0]
    b = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 17"))[0]
    fa, pa = _fp(ctx, a)
    fb, pb = _fp(ctx, b)
    assert fa == fb
    assert [p.value for p in pa] == [5]
    assert [p.value for p in pb] == [17]
    assert "P0:INTEGER" in fa


def test_unparameterized_fingerprints_stay_distinct(ctx):
    fa, _ = _fp(ctx, _plan(ctx, "SELECT a FROM t WHERE a > 5"))
    fb, _ = _fp(ctx, _plan(ctx, "SELECT a FROM t WHERE a > 17"))
    assert fa != fb  # DSQL_PARAM_PLANS=0 behavior: value-baked identity


# ---------------------------------------------------------------------------
# result-cache canonicalization (runtime/result_cache._canon_rex)
# ---------------------------------------------------------------------------

def test_canon_default_is_value_bearing(ctx):
    a = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 5"))[0]
    b = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 17"))[0]
    ta, va, _ = rc.canonical_plan(a, ctx)
    tb, vb, _ = rc.canonical_plan(b, ctx)
    assert not va and not vb  # RexParam must not mark the plan volatile
    assert ta != tb
    assert "P0:INTEGER=5" in ta and "P0:INTEGER=17" in tb


def test_canon_shape_mode_is_value_free(ctx):
    a = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 5"))[0]
    b = parameterize_plan(_plan(ctx, "SELECT a FROM t WHERE a > 17"))[0]
    assert (rc.canonical_plan(a, ctx, shape=True)[0]
            == rc.canonical_plan(b, ctx, shape=True)[0])


def test_flight_recorder_fingerprint_shared_across_literals(ctx):
    from dask_sql_tpu.runtime.flight_recorder import plan_fingerprint
    fa = plan_fingerprint(_plan(ctx, "SELECT a FROM t WHERE a > 5"), ctx)
    fb = plan_fingerprint(_plan(ctx, "SELECT a FROM t WHERE a > 17"), ctx)
    fc = plan_fingerprint(_plan(ctx, "SELECT a FROM t WHERE b > 1.0"), ctx)
    assert fa is not None and fa == fb
    assert fa != fc


def test_statistics_use_param_values():
    from dask_sql_tpu.runtime.statistics import _literal_value
    from dask_sql_tpu.types import INTEGER
    p = N.RexParam(0, 42, INTEGER)
    assert _literal_value(p) == 42.0
