"""Spill manager unit tests (runtime/spill.py) + host partition codes
(physical/morsel.py partition_codes).

The store's contract: byte-accounted three-tier chunk runs whose payloads
survive any tier movement bit-for-bit, typed SpillCorrupt on unreadable
disk chunks, and partition codes that send EQUAL keys to EQUAL partitions
regardless of which side of a join (mask presence, physical dtype, or
string dictionary) they came from — the property grace-hash joins are
built on.
"""
import os

import numpy as np
import pytest

from dask_sql_tpu.physical.morsel import partition_codes
from dask_sql_tpu.runtime import spill as spill_mod
from dask_sql_tpu.runtime.spill import SpillCorrupt, SpillStore
from dask_sql_tpu.types import BIGINT, DOUBLE, VARCHAR


@pytest.fixture
def store(monkeypatch, tmp_path):
    monkeypatch.setenv("DSQL_SPILL_MB", "64")
    monkeypatch.setenv("DSQL_SPILL_DIR", str(tmp_path))
    return SpillStore()


def _cols(n, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.random(n)
    mask = rng.random(n) > 0.1
    ints = rng.integers(0, 1000, n)
    return [(data, mask, DOUBLE, None), (ints, None, BIGINT, None)]


def _assert_cols_equal(got, want):
    assert len(got) == len(want)
    for (gd, gm, *_), (wd, wm, *_) in zip(got, want):
        np.testing.assert_array_equal(gd, wd)
        if wm is None:
            assert gm is None
        else:
            np.testing.assert_array_equal(gm, wm)


# ---------------------------------------------------------------------------
# host tier
# ---------------------------------------------------------------------------

def test_host_round_trip(store):
    a, b = _cols(100, seed=1), _cols(50, seed=2)
    assert store.put_host("r1", ["x", "y"], a) == 0
    assert store.put_host("r1", ["x", "y"], b) == 1
    assert store.n_chunks("r1") == 2
    assert store.run_rows("r1") == 150
    names, got = store.get_host_cols("r1", 0)
    assert names == ["x", "y"]
    _assert_cols_equal(got, a)
    _, got = store.get_host_cols("r1", 1)
    _assert_cols_equal(got, b)
    meta_names, stypes, dicts, rows = store.chunk_meta("r1", 1)
    assert meta_names == ["x", "y"]
    assert stypes == [DOUBLE, BIGINT]
    assert rows == 50
    assert store.host_bytes > 0
    store.free_run("r1")
    assert store.host_bytes == 0
    assert not store.has_run("r1")


def test_stats_and_snapshot(store):
    store.put_host("r1", ["x", "y"], _cols(10))
    s = store.stats()
    assert s["runs"] == 1 and s["chunks"] == 1 and s["host_bytes"] > 0
    snap = store.runs_snapshot()
    assert len(snap) == 1
    assert snap[0]["run"] == "r1"
    assert snap[0]["host_chunks"] == 1 and snap[0]["disk_chunks"] == 0


# ---------------------------------------------------------------------------
# disk tier
# ---------------------------------------------------------------------------

def test_disk_flush_lru_order_and_reload(store, monkeypatch, tmp_path):
    # ~0.9 MB per chunk against a 2 MB budget: chunk 0 (coldest) must
    # flush to disk when chunk 2 arrives, hotter chunks stay resident
    monkeypatch.setenv("DSQL_SPILL_MB", "2")
    chunks = [_cols(60_000, seed=i) for i in range(3)]
    for c in chunks:
        store.put_host("r", ["x", "y"], c)
    snap = store.runs_snapshot()[0]
    assert snap["disk_chunks"] >= 1
    assert store.disk_bytes > 0
    # the COLDEST chunk went first
    tier0 = store.get_chunk("r", 2)[0]
    assert tier0 == "host"
    assert any(f.endswith(".npz") for f in os.listdir(tmp_path))
    # reload round-trips bit-for-bit and consumes the file
    _, got = store.get_host_cols("r", 0)
    _assert_cols_equal(got, chunks[0])
    store.free_run("r")
    assert store.host_bytes == 0 and store.disk_bytes == 0
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".npz")]


def test_reload_never_self_evicts(store, monkeypatch):
    # regression: a chunk LARGER than the whole host budget must still be
    # readable after its disk round-trip — the budget sweep that runs
    # after a load pins the chunk being handed out (an unpinned sweep
    # flushed it straight back and the caller saw None payloads)
    monkeypatch.setenv("DSQL_SPILL_MB", "1")
    big = _cols(200_000, seed=7)  # ~2.4 MB > 1 MB budget
    store.put_host("r", ["x", "y"], big)
    assert store.runs_snapshot()[0]["disk_chunks"] == 1
    _, got = store.get_host_cols("r", 0)
    _assert_cols_equal(got, big)


def test_corrupt_disk_chunk_raises_typed(store, monkeypatch, tmp_path):
    monkeypatch.setenv("DSQL_SPILL_MB", "1")
    store.put_host("r", ["x", "y"], _cols(200_000, seed=3))
    files = [f for f in os.listdir(tmp_path) if f.endswith(".npz")]
    assert files
    with open(tmp_path / files[0], "wb") as f:
        f.write(b"not an npz payload")
    with pytest.raises(SpillCorrupt):
        store.get_chunk("r", 0)


# ---------------------------------------------------------------------------
# device tier
# ---------------------------------------------------------------------------

def _device_table(n=64, seed=0):
    import jax.numpy as jnp

    from dask_sql_tpu.table import Column, Table

    rng = np.random.default_rng(seed)
    host = rng.random(n)
    return host, Table(["v"], [Column(jnp.asarray(host), DOUBLE, None,
                                      None)])


def test_device_round_trip_and_shrink_demotion(store):
    host, table = _device_table(seed=11)
    store.put_table("d", table)
    tier, names, payload = store.get_chunk("d", 0)
    assert tier == "device" and names == ["v"]
    assert store.device_bytes > 0
    assert store.peak_device_bytes >= store.device_bytes
    # ledger-tenant hook: shrink demotes device chunks to host layout
    store.shrink_device_to(0)
    assert store.device_bytes == 0
    tier, _, _ = store.get_chunk("d", 0)
    assert tier == "host"
    _, got = store.get_host_cols("d", 0)
    np.testing.assert_allclose(got[0][0], host)


def test_device_cap_demotes_oversized_puts(store, monkeypatch):
    monkeypatch.setenv("DSQL_SPILL_DEVICE_MB", "0")
    _, table = _device_table(seed=12)
    store.put_table("d", table)
    tier, _, _ = store.get_chunk("d", 0)
    assert tier == "host"
    assert store.device_bytes == 0


# ---------------------------------------------------------------------------
# partition codes (physical/morsel.py)
# ---------------------------------------------------------------------------

def test_partition_codes_conservation_and_null_slots():
    rng = np.random.default_rng(0)
    n, P = 5000, 8
    keys = rng.integers(0, 100, n)
    mask = rng.random(n) > 0.05
    cols = [(keys, mask, BIGINT, None)]
    codes = partition_codes(cols, [0], P)
    assert codes.dtype == np.int64
    # NULL keys -> dead slot -1; every live row routed in [0, P)
    np.testing.assert_array_equal(codes == -1, ~mask)
    live = codes[mask]
    assert live.min() >= 0 and live.max() < P
    # conservation: regrouping by code loses no live rows
    assert sum((codes == p).sum() for p in range(P)) == mask.sum()


def test_partition_codes_mask_presence_consistent():
    # one side's key column carries a mask, the other side's doesn't —
    # equal keys MUST land in equal partitions anyway
    keys = np.arange(1000, dtype=np.int64) % 97
    with_mask = partition_codes([(keys, np.ones(1000, bool), BIGINT,
                                  None)], [0], 16)
    without = partition_codes([(keys, None, BIGINT, None)], [0], 16)
    np.testing.assert_array_equal(with_mask, without)


def test_partition_codes_mixed_dtype_consistent():
    # int okey on one side, float okey on the other (TPC-H Q3 after a
    # NULL-able encode): 5 and 5.0 must agree on their partition
    ints = np.arange(2000, dtype=np.int64) % 311
    floats = ints.astype(np.float64)
    ci = partition_codes([(ints, None, BIGINT, None)], [0], 8)
    cf = partition_codes([(floats, None, DOUBLE, None)], [0], 8)
    np.testing.assert_array_equal(ci, cf)


def test_partition_codes_cross_dictionary_consistent():
    # the same string VALUES under two different (sorted) dictionaries:
    # codes differ per table, value hashes must not
    values = np.array(["ape", "bat", "cat", "dog", "eel"], dtype=object)
    d1 = np.array(["ape", "bat", "cat", "dog", "eel"], dtype=object)
    d2 = np.array(["ant", "ape", "bat", "cat", "dog", "eel", "fox"],
                  dtype=object)
    codes1 = np.array([0, 1, 2, 3, 4] * 40, dtype=np.int32)
    codes2 = np.array([1, 2, 3, 4, 5] * 40, dtype=np.int32)  # same values
    c1 = partition_codes([(codes1, None, VARCHAR, d1)], [0], 8)
    c2 = partition_codes([(codes2, None, VARCHAR, d2)], [0], 8)
    np.testing.assert_array_equal(c1, c2)


def test_partition_codes_multi_key():
    rng = np.random.default_rng(1)
    n, P = 3000, 16
    a = rng.integers(0, 50, n)
    b = rng.integers(0, 50, n)
    m = rng.random(n) > 0.03
    codes = partition_codes([(a, None, BIGINT, None),
                             (b, m, BIGINT, None)], [0, 1], P)
    np.testing.assert_array_equal(codes == -1, ~m)
    # equal (a, b) pairs agree on partition
    lookup = {}
    for i in range(n):
        if not m[i]:
            continue
        key = (int(a[i]), int(b[i]))
        assert lookup.setdefault(key, int(codes[i])) == int(codes[i])
