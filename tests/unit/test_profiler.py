"""Device-level query profiler (runtime/profiler.py) + perf sentinel.

Degradation is the contract under test: every consumer must survive a
backend with no cost model (``cost_analysis`` absent/raising/None/empty),
the disabled path must never import the profiler module, and the sentinel
must judge old-format bench artifacts without a headline block.
"""
import json
import os
import subprocess
import sys

import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import profiler as prof
from dask_sql_tpu.runtime import telemetry as tel

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "..", "scripts")
sys.path.insert(0, SCRIPTS)

import perf_sentinel as ps  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_profiler():
    prof.reset()
    yield
    prof.reset()


# ---------------------------------------------------------------------------
# cost_summary degradation matrix
# ---------------------------------------------------------------------------

class _Compiled:
    def __init__(self, ca):
        self._ca = ca

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca


@pytest.mark.parametrize("ca", [
    None,                                   # backend returns nothing
    RuntimeError("no cost model"),          # backend raises
    [],                                     # empty list
    {},                                     # empty dict
    [{"flops": 0.0, "bytes accessed": 0}],  # all-zero = no signal
    [{"flops": float("nan"), "bytes accessed": float("inf")}],
    [{"flops": "garbage"}],
])
def test_cost_summary_degrades_to_none(ca):
    assert prof.cost_summary(_Compiled(ca)) is None


def test_cost_summary_absent_method():
    assert prof.cost_summary(object()) is None


def test_cost_summary_list_and_dict_forms():
    want = {"flops": 12.0, "bytes": 34.0, "transcendentals": 2.0}
    payload = {"flops": 12.0, "bytes accessed": 34.0, "transcendentals": 2.0}
    assert prof.cost_summary(_Compiled([payload])) == want
    assert prof.cost_summary(_Compiled(dict(payload))) == want


def test_cost_summary_real_jit():
    import jax
    import jax.numpy as jnp
    compiled = jax.jit(lambda x: jnp.sum(x * 2.0)).lower(
        jnp.arange(128, dtype=jnp.float32)).compile()
    cost = prof.cost_summary(compiled)
    assert cost is not None
    assert cost["flops"] > 0 or cost["bytes"] > 0


# ---------------------------------------------------------------------------
# ledger: keys, record/read, scheduler rung, error
# ---------------------------------------------------------------------------

def test_fp_key_none_and_stability():
    assert prof._fp_key(None) is None
    assert prof._fp_key("") is None
    a, b = prof._fp_key("plan-text"), prof._fp_key("plan-text")
    assert a == b and isinstance(a, str)
    assert prof._fp_key("other-plan") != a


def test_ledger_roundtrip_overwrites_not_double_counts():
    cost = {"flops": 10.0, "bytes": 100.0, "transcendentals": 0.0}
    prof.record_program_cost("fp1", "digA", cost)
    prof.record_program_cost("fp1", "digA", cost)  # repeat: overwrite
    prof.record_program_cost("fp1", "digB", {"flops": 1.0, "bytes": 7.0})
    got = prof.program_costs("fp1")
    assert set(got) == {"digA", "digB"}
    assert got["digA"]["bytes"] == 100.0
    prof.record_measured("digA", nbytes=50, wall_ms=1.5, device_ms=0.5)
    got = prof.program_costs("fp1")["digA"]
    assert got["measured_bytes"] == 50.0
    assert got["measured_ms"] == 1.5
    assert got["measured_device_ms"] == 0.5


def test_record_program_cost_none_is_noop():
    prof.record_program_cost("fp1", "digA", None)
    prof.record_program_cost(None, "digA", {"bytes": 1.0})
    assert prof.program_costs("fp1") == {}


def test_cost_error():
    assert prof.cost_error(None, 10) is None
    assert prof.cost_error(10, None) is None
    assert prof.cost_error(0, 10) is None
    assert prof.cost_error(10, 0) is None
    assert prof.cost_error(150.0, 100.0) == pytest.approx(0.5)
    assert prof.cost_error(50.0, 100.0) == pytest.approx(0.5)


def test_scheduler_rung_skipped_without_env(monkeypatch):
    """estimate_working_set must not consult (or import-fail on) the
    profiler when DSQL_PROFILE is off — and must survive a plan the
    fingerprinter rejects when it is on."""
    from dask_sql_tpu.runtime import scheduler as sched
    from dask_sql_tpu.sql.parser import parse_sql
    monkeypatch.delenv("DSQL_PROFILE", raising=False)
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    sql = "SELECT SUM(a) AS s FROM t"
    plan = c._get_plan(parse_sql(sql)[0].query, sql)
    est, source = sched.estimate_working_set(plan, c)
    assert est > 0 and source in ("heuristic", "stats")
    monkeypatch.setenv("DSQL_PROFILE", "1")
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    est2, source2 = sched.estimate_working_set(plan, c)
    # nothing captured yet: the rung yields, heuristic serves
    assert est2 > 0 and source2 == "heuristic"


def test_cost_model_rung_serves_after_capture(monkeypatch):
    monkeypatch.setenv("DSQL_PROFILE", "1")
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    from dask_sql_tpu.runtime import scheduler as sched
    from dask_sql_tpu.sql.parser import parse_sql
    c = Context()
    c.create_table("t", {"a": list(range(100))})
    sql = "SELECT SUM(a) AS s FROM t"
    c.sql(sql, return_futures=False)
    plan = c._get_plan(parse_sql(sql)[0].query, sql)
    before = tel.REGISTRY.get("estimate_from_cost_model")
    est, source = sched.estimate_working_set(plan, c)
    assert source == "cost_model", (est, source)
    assert est > 0
    assert tel.REGISTRY.get("estimate_from_cost_model") == before + 1


# ---------------------------------------------------------------------------
# memory sampling
# ---------------------------------------------------------------------------

def test_device_memory_rows_degrade_to_zeros():
    rows = prof.device_memory_rows()
    assert rows, "jax is initialized in tests: rows expected"
    for r in rows:
        assert r["bytes_in_use"] >= 0
        assert r["peak_bytes_in_use"] >= 0
        assert {"id", "platform", "kind", "bytes_limit"} <= set(r)


def test_sample_ring_and_gauges():
    n0 = len(prof.snapshots())
    prof.sample()
    snaps = prof.snapshots()
    assert len(snaps) == n0 + 1
    assert "unix" in snaps[-1] and "devices" in snaps[-1]
    assert tel.REGISTRY.get_gauge("profile_hbm_bytes_in_use") >= 0


def test_engine_section_shape():
    prof.record_program_cost("fp1", "digA", {"flops": 1.0, "bytes": 2.0})
    sec = prof.engine_section()
    assert sec["enabled"] is True
    assert sec["costPlans"] == 1 and sec["costPrograms"] == 1
    assert sec["sampleMs"] >= 10.0


# ---------------------------------------------------------------------------
# EXPLAIN PROFILE: parser + renderer
# ---------------------------------------------------------------------------

def test_parser_explain_profile_flag():
    from dask_sql_tpu.sql.parser import parse_sql
    (stmt,) = parse_sql("EXPLAIN PROFILE SELECT 1")
    assert stmt.profile is True and stmt.analyze is False
    (stmt,) = parse_sql("EXPLAIN ANALYZE SELECT 1")
    assert stmt.profile is False and stmt.analyze is True
    (stmt,) = parse_sql("EXPLAIN SELECT 1")
    assert stmt.profile is False and stmt.analyze is False


def _plan_lines(ctx, sql):
    out = ctx.sql(sql, return_futures=False)
    return [str(l) for l in out["PLAN"]]


def test_explain_profile_disabled_points_and_skips(monkeypatch):
    monkeypatch.delenv("DSQL_PROFILE", raising=False)
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    compiles = tel.REGISTRY.get("compiles")
    lines = _plan_lines(c, "EXPLAIN PROFILE SELECT SUM(a) AS s FROM t")
    assert any("profile: disabled" in l for l in lines)
    assert not any(l.startswith("-- stage") for l in lines)
    # the query itself must NOT have executed (nothing compiled)
    assert tel.REGISTRY.get("compiles") == compiles


def test_explain_profile_renders_stage_and_devices(monkeypatch):
    monkeypatch.setenv("DSQL_PROFILE", "1")
    # the estimate line reads the admission span: arm the scheduler
    # (pinned off for unrelated suites by conftest)
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    c = Context()
    c.create_table("t", {"a": list(range(500)),
                         "b": [i % 5 for i in range(500)]})
    lines = _plan_lines(c, "EXPLAIN PROFILE "
                           "SELECT b, SUM(a) AS s FROM t GROUP BY b")
    assert any(l.startswith("-- profile: wall=") for l in lines)
    stage = [l for l in lines if l.startswith("-- stage")]
    assert stage, lines
    assert any("flops=" in l for l in stage)
    import jax
    dev = [l for l in lines if l.startswith("-- device")]
    assert len(dev) == len(jax.local_devices())
    assert any(l.startswith("-- estimate: source=") for l in lines)


def test_explain_profile_bypasses_result_cache(monkeypatch):
    """A previously-run (cached) query must still profile a REAL
    execution — the lookup is bypassed, the store refreshed."""
    monkeypatch.setenv("DSQL_PROFILE", "1")
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    c = Context()
    c.create_table("t", {"a": list(range(100))})
    q = "SELECT SUM(a) AS s FROM t"
    c.sql(q, return_futures=False)
    c.sql(q, return_futures=False)   # primes a cache hit
    hits0 = tel.REGISTRY.get("result_cache_hits")
    lines = _plan_lines(c, "EXPLAIN PROFILE " + q)
    assert tel.REGISTRY.get("result_cache_hits") == hits0
    assert any(l.startswith("-- stage") for l in lines)
    assert c._rc_bypass is False  # restored even on success


# ---------------------------------------------------------------------------
# disabled-path tripwire: zero profiler imports
# ---------------------------------------------------------------------------

def test_profiler_never_imports_when_disabled():
    code = (
        "import sys\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3, 4]})\n"
        "c.sql('SELECT SUM(a) AS s FROM t', return_futures=False)\n"
        "assert 'dask_sql_tpu.runtime.profiler' not in sys.modules, \\\n"
        "    'hot path imported the profiler with DSQL_PROFILE unset'\n"
        "print('tripwire ok')\n"
    )
    env = dict(os.environ)
    env.pop("DSQL_PROFILE", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=600)
    assert proc.returncode == 0, proc.stderr.decode()[-800:]
    assert b"tripwire ok" in proc.stdout


# ---------------------------------------------------------------------------
# exchange collective-bytes estimators
# ---------------------------------------------------------------------------

def test_exchange_collective_byte_estimators():
    import jax.numpy as jnp
    from dask_sql_tpu.parallel import exchange as X
    a = jnp.zeros(10, dtype=jnp.int64)     # 80 bytes
    b = jnp.zeros(4, dtype=jnp.float32)    # 16 bytes
    # all_gather: every shard's bytes land on every device
    assert X.gather_bytes([a], 4) == 80 * 4 * 4
    assert X.gather_bytes([a, b], 2) == (80 + 16) * 2 * 2
    # psum: one reduced copy lands on every device
    assert X.psum_bytes([a], 4) == 80 * 4
    assert X.psum_bytes([a, b], 2) == (80 + 16) * 2


# ---------------------------------------------------------------------------
# system.devices
# ---------------------------------------------------------------------------

def test_system_devices_table():
    import jax
    c = Context()
    out = c.sql("SELECT device_id, platform, bytes_in_use, peak_bytes_in_use"
                " FROM system.devices", return_futures=False)
    assert len(out) == len(jax.local_devices())
    assert sorted(out["device_id"]) == sorted(
        d.id for d in jax.local_devices())


# ---------------------------------------------------------------------------
# perf sentinel
# ---------------------------------------------------------------------------

HL = {"schema": 1, "warm_exec_geomean_sec": 1.0, "first_arrival_sec": 4.0,
      "program_store_hit_rate": 0.9, "vs_pandas_geomean": 2.0,
      "compile_errors": 0}


def test_extract_headline_new_format():
    doc = {"metric": "tpch_q1_q22_geomean_wall", "value": 1.0,
           "headline": dict(HL), "detail": {}}
    assert ps.extract_headline(doc) == HL
    # wrapped artifact form
    assert ps.extract_headline({"n": 6, "rc": 0, "parsed": doc}) == HL


def test_extract_headline_derives_from_old_detail():
    doc = {"metric": "tpch_q1_q22_geomean_wall", "value": 0.5,
           "vs_baseline": 1.3,
           "detail": {"first_arrival_sec": {"1": 2.0, "3": 8.0},
                      "program_store_hit_rate": 0.8,
                      "compiled_stats": {"compile_errors": 2}}}
    hl = ps.extract_headline(doc)
    assert hl["warm_exec_geomean_sec"] == 0.5
    assert hl["first_arrival_sec"] == pytest.approx(4.0)
    assert hl["program_store_hit_rate"] == 0.8
    assert hl["vs_pandas_geomean"] == 1.3
    assert hl["compile_errors"] == 2


def test_extract_headline_unusable():
    assert ps.extract_headline({"n": 3, "rc": 124, "parsed": None}) is None
    assert ps.extract_headline({"metric": "other_metric",
                                "value": 9, "detail": {}}) is None


def test_compare_directions():
    base = dict(HL)
    # identical: clean
    reg, verd = ps.compare(base, dict(base), 0.25)
    assert not reg and len(verd) == 5
    # lower-better regresses upward
    cur = dict(base, warm_exec_geomean_sec=2.0)
    reg, _ = ps.compare(base, cur, 0.25)
    assert [r["metric"] for r in reg] == ["warm_exec_geomean_sec"]
    # higher-better regresses downward
    cur = dict(base, program_store_hit_rate=0.5)
    reg, _ = ps.compare(base, cur, 0.25)
    assert [r["metric"] for r in reg] == ["program_store_hit_rate"]
    # improvements never flag
    cur = dict(base, warm_exec_geomean_sec=0.1, vs_pandas_geomean=10.0)
    reg, _ = ps.compare(base, cur, 0.25)
    assert not reg
    # inside the band: tolerated
    cur = dict(base, warm_exec_geomean_sec=1.2)
    reg, _ = ps.compare(base, cur, 0.25)
    assert not reg
    # compile_errors may never increase, tolerance or not
    cur = dict(base, compile_errors=1)
    reg, _ = ps.compare(base, cur, 0.25)
    assert [r["metric"] for r in reg] == ["compile_errors"]
    # None on either side: metric skipped, not crashed
    cur = dict(base, first_arrival_sec=None)
    reg, verd = ps.compare(base, cur, 0.25)
    assert not reg and len(verd) == 4


def test_run_pass_and_fail(tmp_path):
    base = tmp_path / "base.json"
    cur = tmp_path / "cur.json"
    base.write_text(json.dumps({"headline": dict(HL)}))
    cur.write_text(json.dumps(
        {"headline": dict(HL, warm_exec_geomean_sec=0.9)}))
    code, report = ps.run(str(tmp_path), str(cur), str(base))
    assert code == 0 and report["status"] == "pass"
    cur.write_text(json.dumps(
        {"headline": dict(HL, warm_exec_geomean_sec=5.0)}))
    code, report = ps.run(str(tmp_path), str(cur), str(base))
    assert code == 1 and report["regressions"]
    # unreadable explicit input is an error, not a silent pass
    code, _ = ps.run(str(tmp_path), str(tmp_path / "missing.json"),
                     str(base))
    assert code == 2


def test_run_nothing_comparable_passes(tmp_path):
    code, report = ps.run(str(tmp_path))
    assert code == 0
    assert "nothing comparable" in report["status"]


def test_sentinel_on_repo_artifacts():
    """The committed trajectory must pass the committed baseline — the
    same invocation ci_local.sh [2l] runs."""
    root = os.path.join(os.path.dirname(__file__), "..", "..")
    code, report = ps.run(root)
    assert code == 0, report
