"""Optional-dependency integrations with the dependency faked: intake
catalog ingestion (reference input_utils/intake.py:14-34) and the IPython
CodeMirror syntax-highlighting payload (reference integrations/ipython.py:91-133).
"""
import sys
import types

import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context


@pytest.fixture()
def fake_intake(monkeypatch):
    """A minimal stand-in for the intake package: Catalog is a dict of
    entries whose .read() returns a pandas frame."""
    intake = types.ModuleType("intake")
    catalog_mod = types.ModuleType("intake.catalog")

    class Source:
        def __init__(self, df, **kwargs):
            self.df = df
            self.kwargs = kwargs

        def __call__(self, **kwargs):
            return Source(self.df, **kwargs)

        def read(self):
            return self.df

    class Catalog:
        def __init__(self):
            self._entries = {}

        def __setitem__(self, k, v):
            self._entries[k] = v

        def __getitem__(self, k):
            return self._entries[k]

    catalog_mod.Catalog = Catalog
    intake.catalog = catalog_mod
    opened = {}

    def open_catalog(path, **kwargs):
        opened["path"] = path
        opened["kwargs"] = kwargs
        cat = Catalog()
        cat["t"] = Source(pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "x"]}))
        return cat

    intake.open_catalog = open_catalog
    monkeypatch.setitem(sys.modules, "intake", intake)
    monkeypatch.setitem(sys.modules, "intake.catalog", catalog_mod)
    return intake, Catalog, Source, opened


def test_intake_catalog_object_ingestion(fake_intake):
    _, Catalog, Source, _ = fake_intake
    cat = Catalog()
    cat["sales"] = Source(pd.DataFrame({"v": [1.0, 2.0, 4.0]}))
    c = Context()
    c.create_table("sales", cat)
    out = c.sql("SELECT SUM(v) AS s FROM sales", return_futures=False)
    assert float(out["s"][0]) == 7.0


def test_intake_catalog_path_with_format(fake_intake):
    _, _, _, opened = fake_intake
    c = Context()
    c.create_table("t", "/some/catalog.yaml", format="intake",
                   intake_table_name="t",
                   catalog_kwargs={"ttl": 60})
    assert opened["path"] == "/some/catalog.yaml"
    assert opened["kwargs"] == {"ttl": 60}
    out = c.sql("SELECT b, COUNT(*) AS n FROM t GROUP BY b ORDER BY b",
                return_futures=False)
    assert out["n"].tolist() == [2, 1]


def test_highlighting_mime_type_tracks_live_registry():
    from dask_sql_tpu.integrations.ipython import (highlighting_js,
                                                   highlighting_mime_type)
    from dask_sql_tpu.physical.rex.ops import OPERATION_MAPPING

    mime = highlighting_mime_type()
    # every live operator is a highlighted keyword (lowercased)
    for op in OPERATION_MAPPING:
        assert mime["keywords"].get(str(op).lower()), op
    assert mime["builtin"].get("varchar")
    assert mime["atoms"] == {"false": True, "true": True, "null": True}
    js = highlighting_js()
    assert "text/x-dasksql" in js and "CodeMirror.defineMIME" in js


def test_ipython_magic_registers_and_highlights(monkeypatch):
    registered = {}
    shipped = {}

    magic_mod = types.ModuleType("IPython.core.magic")

    def register_line_cell_magic(fn):
        registered["fn"] = fn
        return fn

    magic_mod.register_line_cell_magic = register_line_cell_magic
    display_mod = types.ModuleType("IPython.core.display")
    display_mod.display_javascript = (
        lambda js, raw=False: shipped.update(js=js, raw=raw))
    core_mod = types.ModuleType("IPython.core")
    core_mod.magic = magic_mod
    core_mod.display = display_mod
    ipython_mod = types.ModuleType("IPython")
    ipython_mod.core = core_mod
    ipython_mod.get_ipython = lambda: None
    for name, mod in [("IPython", ipython_mod), ("IPython.core", core_mod),
                      ("IPython.core.magic", magic_mod),
                      ("IPython.core.display", display_mod)]:
        monkeypatch.setitem(sys.modules, name, mod)

    from dask_sql_tpu.integrations.ipython import ipython_integration

    c = Context()
    c.create_table("t", pd.DataFrame({"a": np.arange(4)}))
    ipython_integration(c)
    assert registered["fn"].__name__ == "sql"
    assert shipped["raw"] is True and "text/x-dasksql" in shipped["js"]
    out = registered["fn"]("SELECT COUNT(*) AS n FROM t")
    assert out["n"].tolist() == [4]
