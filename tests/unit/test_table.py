"""Columnar Table unit tests (reference: tests/unit/test_datacontainer.py)."""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu.table import Column, Scalar, Table
from dask_sql_tpu.types import BIGINT, DOUBLE, VARCHAR, SqlType


def test_roundtrip_pandas():
    df = pd.DataFrame({
        "i": [1, 2, 3],
        "f": [1.5, np.nan, 2.5],
        "s": ["x", None, "zz"],
        "ni": pd.array([1, None, 3], dtype="Int64"),
        "d": pd.to_datetime(["2020-01-01", "2020-06-01", None]),
        "b": [True, False, True],
    })
    t = Table.from_pandas(df)
    out = t.to_pandas()
    assert list(out["i"]) == [1, 2, 3]
    assert out["f"][0] == 1.5 and np.isnan(out["f"][1])
    assert list(out["s"][[0, 2]]) == ["x", "zz"] and pd.isna(out["s"][1])
    assert out["ni"][0] == 1 and out["ni"][1] is None
    assert out["d"][0] == pd.Timestamp("2020-01-01")
    assert pd.isna(out["d"][2])


def test_limit_to_and_rename():
    t = Table.from_pydict({"a": [1, 2], "b": [3, 4]})
    t2 = t.limit_to(["b"])
    assert t2.names == ["b"]
    t3 = t.rename({"a": "x"})
    assert t3.names == ["x", "b"]
    # renames are zero-copy: same underlying arrays
    assert t3.columns[0] is t.columns[0]


def test_take_and_slice():
    t = Table.from_pydict({"a": [1, 2, 3, 4]})
    assert t.take(np.array([3, 0])).to_pylist() == [[4], [1]]
    assert t.slice(1, 3).to_pylist() == [[2], [3]]


def test_string_dictionary():
    col = Column.from_numpy(np.array(["b", "a", "b", None], dtype=object))
    assert col.stype.is_string
    assert col.null_count() == 1
    decoded = col.decode()
    assert list(decoded[:3]) == ["b", "a", "b"] and decoded[3] is None
    ranks = col.dict_ranks()
    assert int(ranks.data[0]) > int(ranks.data[1])  # 'b' > 'a'


def test_from_scalar():
    col = Column.from_scalar(Scalar(5, BIGINT), 3)
    assert col.to_pylist() == [5, 5, 5]
    null_col = Column.from_scalar(Scalar(None, DOUBLE), 2)
    assert null_col.null_count() == 2


def test_column_types():
    t = Table.from_pydict({"a": np.array([1, 2], dtype=np.int32)})
    assert t.columns[0].stype.name == "INTEGER"
    t = Table.from_pydict({"a": np.array([1.0, 2.0], dtype=np.float32)})
    assert t.columns[0].stype.name == "FLOAT"
