"""Type system unit tests (reference: tests/unit/test_mapping.py)."""
import datetime

import numpy as np
import pytest

from dask_sql_tpu import types as T


def test_numpy_to_sql():
    assert T.sql_type_from_numpy(np.dtype("int64")).name == "BIGINT"
    assert T.sql_type_from_numpy(np.dtype("int32")).name == "INTEGER"
    assert T.sql_type_from_numpy(np.dtype("float64")).name == "DOUBLE"
    assert T.sql_type_from_numpy(np.dtype("bool")).name == "BOOLEAN"
    assert T.sql_type_from_numpy(np.dtype("datetime64[ns]")).name == "TIMESTAMP"
    assert T.sql_type_from_numpy(np.dtype("object")).name == "VARCHAR"
    assert T.sql_type_from_numpy(np.dtype("uint32")).name == "BIGINT"


def test_promote():
    assert T.promote(T.INTEGER, T.BIGINT).name == "BIGINT"
    assert T.promote(T.INTEGER, T.DOUBLE).name == "DOUBLE"
    assert T.promote(T.NULLTYPE, T.VARCHAR).name == "VARCHAR"
    assert T.promote(T.DATE, T.TIMESTAMP).name == "TIMESTAMP"
    assert T.promote(T.DATE, T.INTERVAL_DAY_TIME).name == "DATE"
    with pytest.raises(TypeError):
        T.promote(T.BOOLEAN, T.DATE)


def test_parse_type_name():
    assert T.parse_type_name("INT").name == "INTEGER"
    assert T.parse_type_name("STRING").name == "VARCHAR"
    assert T.parse_type_name("DECIMAL", 10, 2).precision == 10
    with pytest.raises(NotImplementedError):
        T.parse_type_name("BLOB")


def test_value_conversion_roundtrip():
    d = datetime.date(2020, 3, 1)
    phys = T.python_value_to_physical(d, T.DATE)
    assert T.physical_to_python_value(phys, T.DATE) == d

    ts = datetime.datetime(2021, 7, 1, 12, 30, 45, 123456)
    phys = T.python_value_to_physical(ts, T.TIMESTAMP)
    assert T.physical_to_python_value(phys, T.TIMESTAMP) == ts

    td = datetime.timedelta(days=2, hours=3)
    phys = T.python_value_to_physical(td, T.INTERVAL_DAY_TIME)
    assert T.physical_to_python_value(phys, T.INTERVAL_DAY_TIME) == td


def test_string_date_parsing():
    assert T.python_value_to_physical("1970-01-02", T.DATE) == 1
    assert T.python_value_to_physical("1970-01-01 00:00:01", T.TIMESTAMP) == 1_000_000
