"""Quarantine store + compile watchdog (runtime/quarantine.py): verdict
roundtrip, expiry, half-open probe semantics, corrupt-file tolerance,
cross-"process" sharing (two store instances over one file), and the
watchdog's suspect-mark/lift lifecycle."""
import json
import os
import threading
import time

import pytest

from dask_sql_tpu.runtime import quarantine as Q
from dask_sql_tpu.runtime import telemetry as tel


@pytest.fixture()
def store(tmp_path, monkeypatch):
    path = str(tmp_path / "quarantine.json")
    monkeypatch.setenv("DSQL_QUARANTINE_FILE", path)
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "3600")
    monkeypatch.setenv("DSQL_QUARANTINE_PROBE_S", "3600")
    return Q.QuarantineStore(path)


def test_disabled_without_file(monkeypatch):
    monkeypatch.delenv("DSQL_QUARANTINE_FILE", raising=False)
    s = Q.QuarantineStore()
    assert not s.enabled()
    assert s.check("k") is None
    s.mark("k", "fatal")              # silent no-op
    assert s.check("k") is None


def test_mark_check_clear_roundtrip(store):
    assert store.check("k1") is None
    store.mark("k1", "fatal", reason="boom")
    assert store.check("k1") == "quarantined"
    entry = store.entries()["k1"]
    assert entry["verdict"] == "fatal"
    assert entry["reason"] == "boom"
    assert entry["strikes"] == 1
    store.clear("k1")
    assert store.check("k1") is None
    # clearing an absent key is a no-op
    store.clear("k1")


def test_remark_counts_strikes(store):
    store.mark("k", "hang")
    store.mark("k", "fatal")
    assert store.entries()["k"]["strikes"] == 2
    assert store.entries()["k"]["verdict"] == "fatal"


def test_expiry_then_half_open_probe(store, monkeypatch):
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "0.05")
    monkeypatch.setenv("DSQL_QUARANTINE_PROBE_S", "3600")
    store.mark("k", "fatal")
    assert store.check("k") == "quarantined"
    time.sleep(0.08)
    # expired: exactly ONE caller gets the probe; the entry is re-armed
    # for the probe window so every other caller keeps skipping
    assert store.check("k") == "probe"
    assert store.check("k") == "quarantined"
    # a successful probe lifts the verdict entirely
    store.clear("k")
    assert store.check("k") is None


def test_failed_probe_rearms_full_ttl(store, monkeypatch):
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "0.05")
    store.mark("k", "hang")
    time.sleep(0.08)
    assert store.check("k") == "probe"
    # the probe compile failed again: mark() re-arms with a full TTL
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "3600")
    store.mark("k", "fatal", reason="probe failed")
    assert store.check("k") == "quarantined"
    assert store.entries()["k"]["strikes"] == 2


def test_corrupt_file_reads_as_empty(store):
    store.mark("k", "fatal")
    with open(store.path(), "w") as f:
        f.write("{ this is not json")
    assert store.check("k") is None          # tolerated, not raised
    # and the store still accepts new marks (overwrites the junk)
    store.mark("k2", "hang")
    assert store.check("k2") == "quarantined"
    with open(store.path()) as f:
        assert json.load(f)["k2"]["verdict"] == "hang"


def test_non_dict_entries_are_ignored(store):
    with open(store.path(), "w") as f:
        json.dump({"bad": 17, "ok": {"verdict": "fatal",
                                     "expires_at": time.time() + 60}}, f)
    assert store.check("bad") is None
    assert store.check("ok") == "quarantined"


def test_two_stores_share_one_file(tmp_path, monkeypatch):
    """The cross-process contract, modeled as two independent store
    instances (each with its own mtime cache) over one file."""
    path = str(tmp_path / "q.json")
    monkeypatch.setenv("DSQL_QUARANTINE_TTL_S", "3600")
    a = Q.QuarantineStore(path)
    b = Q.QuarantineStore(path)
    a.mark("k", "fatal", reason="process A crashed")
    assert b.check("k") == "quarantined"
    b.clear("k")
    assert a.check("k") is None


def test_program_key_folds_device_fingerprint():
    k1 = Q.program_key(("plan", "inputs", True))
    k2 = Q.program_key(("plan", "inputs", False))
    assert k1 != k2
    assert k1 == Q.program_key(("plan", "inputs", True))


# ---------------------------------------------------------------------------
# compile watchdog
# ---------------------------------------------------------------------------

def test_watchdog_noop_when_disarmed(monkeypatch):
    monkeypatch.delenv("DSQL_COMPILE_WATCHDOG_S", raising=False)
    wd = Q.CompileWatchdog()
    with wd.watch("k"):
        pass
    assert not wd._entries


def test_watchdog_marks_wedged_section_suspect(store, monkeypatch):
    """A section that exceeds the wall budget gets its fingerprint marked
    'hang' WHILE still running — the cross-process record a killed/wedged
    process leaves behind."""
    monkeypatch.setenv("DSQL_COMPILE_WATCHDOG_S", "0.15")
    wd = Q.CompileWatchdog()
    t0 = tel.REGISTRY.get("watchdog_trips")
    marked_mid_flight = []
    try:
        with wd.watch("wedged", label="test-compile"):
            deadline = time.time() + 5
            while not marked_mid_flight and time.time() < deadline:
                if store.check("wedged") is not None:
                    marked_mid_flight.append(store.entries()["wedged"])
                time.sleep(0.02)
            raise RuntimeError("compile crashed after the hang")
    except RuntimeError:
        pass
    assert marked_mid_flight, "watchdog never marked the wedged section"
    assert marked_mid_flight[0]["verdict"] == "hang"
    assert tel.REGISTRY.get("watchdog_trips") > t0
    # the exception exit leaves the mark in place
    assert store.check("wedged") == "quarantined"


def test_watchdog_clean_finish_lifts_suspect_mark(store, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE_WATCHDOG_S", "0.1")
    wd = Q.CompileWatchdog()
    with wd.watch("slow", label="slow-but-fine"):
        deadline = time.time() + 5
        while store.check("slow") is None and time.time() < deadline:
            time.sleep(0.02)
        assert store.check("slow") is not None
    # finished cleanly: the verdict meant "wedged", not "slow" — lifted
    assert store.check("slow") is None


def test_watchdog_fast_section_never_marked(store, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE_WATCHDOG_S", "5")
    wd = Q.CompileWatchdog()
    with wd.watch("fast"):
        time.sleep(0.01)
    time.sleep(0.15)                  # give the monitor a poll cycle
    assert store.check("fast") is None


def test_watchdog_concurrent_sections_independent(store, monkeypatch):
    monkeypatch.setenv("DSQL_COMPILE_WATCHDOG_S", "0.15")
    wd = Q.CompileWatchdog()
    done = threading.Event()

    def fast():
        with wd.watch("fast2"):
            time.sleep(0.01)
        done.set()

    t = threading.Thread(target=fast)
    with wd.watch("slow2"):
        t.start()
        t.join(timeout=5)
        deadline = time.time() + 5
        while store.check("slow2") is None and time.time() < deadline:
            time.sleep(0.02)
        raise_late = store.check("slow2")
    assert done.is_set()
    assert raise_late is not None
    assert store.check("fast2") is None


# ---------------------------------------------------------------------------
# stable-name contract additions
# ---------------------------------------------------------------------------

def test_quarantine_names_in_stable_contract():
    for name in ("stage_execs", "stage_replays",
                 "stage_replay_saved_stages", "quarantine_skips",
                 "quarantine_probes", "quarantine_marks", "watchdog_trips",
                 "fault_stage_replay", "fault_drain",
                 "server_drain_rejects"):
        assert name in tel.STABLE_COUNTERS
    assert "server_draining" in tel.STABLE_GAUGES
