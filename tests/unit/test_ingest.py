"""Unit tests for continuous ingestion (runtime/ingest.py): WAL
commit/replay, torn-tail tolerance, the kill switch, micro-batch
coalescing, writer backpressure, and snapshot-pinned reads (ISSUE 20).
The cross-process kill -9 recovery and oracle soak live in
scripts/ingest_smoke.py."""
import glob
import os

import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import ingest
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.runtime.resilience import (AdmissionRejected,
                                             IngestBackpressure)
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture(autouse=True)
def ingest_root(tmp_path, monkeypatch):
    root = tmp_path / "ingest"
    monkeypatch.setenv("DSQL_INGEST_DIR", str(root))
    yield root
    ingest._reset_for_tests()


def _base():
    return pd.DataFrame({"k": ["a", "b"], "x": [1.0, 2.0]})


def _wal_lines(root):
    out = []
    for seg in sorted(glob.glob(os.path.join(str(root), "wal", "*.log"))):
        with open(seg, "rb") as f:
            out.extend(ln for ln in f.read().split(b"\n") if ln.strip())
    return out


def test_append_writes_wal_and_applies(ingest_root):
    c = Context()
    c.create_table("t", _base())
    assert c.append_rows("t", [("c", 3.0)]) == 1
    assert c.append_rows("t", {"k": ["d"], "x": [4.0]}) == 1
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 4
    # one committed batch == one WAL line
    assert len(_wal_lines(ingest_root)) == 2
    sec = ingest.engine_section(c)
    assert sec["armed"] and sec["walBytes"] > 0
    assert "root.t" in sec["tables"]


def test_wal_replay_into_fresh_context(ingest_root):
    c1 = Context()
    c1.create_table("t", _base())
    c1.append_rows("t", [("c", 3.0)])
    c1.append_rows("t", [("d", 4.0), ("e", 5.0)])
    ingest._reset_for_tests()  # "process death": close fds, drop the log

    replayed0 = tel.REGISTRY.get("ingest_replayed_rows", 0)
    c2 = Context()
    # the restart path re-registers the base table, then committed WAL
    # batches apply on top of it
    c2.create_table("t", _base())
    got = c2.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 5
    assert tel.REGISTRY.get("ingest_replayed_rows", 0) == replayed0 + 3


def test_torn_wal_tail_is_skipped_not_fatal(ingest_root):
    c1 = Context()
    c1.create_table("t", _base())
    c1.append_rows("t", [("c", 3.0)])
    ingest._reset_for_tests()
    # simulate a crash mid-write: a truncated line with no newline
    (seg,) = glob.glob(os.path.join(str(ingest_root), "wal", "*.log"))
    with open(seg, "ab") as f:
        f.write(b'{"v":1,"crc":99,"p":"{\\"s\\":\\"root\\",\\"t')

    torn0 = tel.REGISTRY.get("ingest_wal_torn_lines", 0)
    c2 = Context()
    c2.create_table("t", _base())
    got = c2.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    # the whole (committed) batch replays; the torn tail was never acked
    assert int(got["n"][0]) == 3
    assert tel.REGISTRY.get("ingest_wal_torn_lines", 0) == torn0 + 1


def test_kill_switch_keeps_append_path_baseline(ingest_root, monkeypatch):
    monkeypatch.setenv("DSQL_INGEST", "0")
    c = Context()
    c.create_table("t", _base())
    assert c.append_rows("t", [("c", 3.0)]) == 1
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 3
    # no WAL directory, no log object: the pre-ingest apply path ran
    assert not os.path.exists(os.path.join(str(ingest_root), "wal"))
    assert getattr(c, "_ingest_log", None) is None


def test_micro_batch_coalesces_to_one_wal_line(ingest_root, monkeypatch):
    monkeypatch.setenv("DSQL_INGEST_BATCH_ROWS", "5")
    monkeypatch.setenv("DSQL_INGEST_BATCH_MS", "60000")
    c = Context()
    c.create_table("t", _base())
    assert c.append_rows("t", [("c", 3.0), ("d", 4.0)]) == 0  # buffered
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 2  # nothing visible until the flush
    assert ingest.engine_section(c)["bufferedRows"] == 2
    # filling the buffer commits the coalesced batch: one WAL line, one
    # catalog swap
    assert c.append_rows("t", [("e", 5.0), ("f", 6.0), ("g", 7.0)]) == 5
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 7
    assert len(_wal_lines(ingest_root)) == 1


def test_backpressure_rejects_before_wal(ingest_root, monkeypatch):
    from dask_sql_tpu.runtime import scheduler
    c = Context()
    c.create_table("t", _base())
    c.append_rows("t", [("c", 3.0)])
    lines0 = len(_wal_lines(ingest_root))
    rejects0 = tel.REGISTRY.get("ingest_backpressure_rejects", 0)
    monkeypatch.setattr(scheduler.get_manager().ledger, "reserve",
                        lambda nbytes: None)
    with pytest.raises(IngestBackpressure) as ei:
        c.append_rows("t", [("d", 4.0)])
    assert isinstance(ei.value, AdmissionRejected)  # rides the 429 path
    assert ei.value.retry_after_s > 0
    # rejected before the commit point: nothing durable, nothing visible
    assert len(_wal_lines(ingest_root)) == lines0
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 3
    assert tel.REGISTRY.get("ingest_backpressure_rejects", 0) == rejects0 + 1


def test_snapshot_pin_isolates_reads_from_writer(ingest_root):
    c = Context()
    c.create_table("t", _base())
    sql = "SELECT k FROM t"
    plan = c._get_plan(parse_sql(sql)[0].query, sql)
    with ingest.pin_scope(c, plan):
        epoch0 = c.table_epoch("root", "t")
        n0 = c.catalog_entry("root", "t").table.num_rows
        c.append_rows("t", [("c", 3.0)])
        # the pinned read still sees the admission-time prefix AND the
        # admission-time epoch (result-cache keys stay consistent)
        assert c.catalog_entry("root", "t").table.num_rows == n0
        assert c.table_epoch("root", "t") == epoch0
    assert c.catalog_entry("root", "t").table.num_rows == n0 + 1
    assert c.table_epoch("root", "t") > epoch0


def test_matview_refreshes_over_ingested_appends(ingest_root, monkeypatch):
    # maintained aggregate state is a result-cache tenant; the session-wide
    # cache-off default (conftest) would degrade the refresh to full
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    c = Context()
    c.create_table("t", _base())
    c.sql("CREATE MATERIALIZED VIEW v AS SELECT SUM(x) AS s FROM t")
    inc0 = tel.REGISTRY.get("mv_refresh_incremental", 0)
    c.append_rows("t", [("c", 3.0)])
    got = c.sql("SELECT s FROM v", return_futures=False)
    assert float(got["s"][0]) == 6.0
    assert tel.REGISTRY.get("mv_refresh_incremental", 0) == inc0 + 1


def test_concurrent_appends_lose_no_rows(ingest_root):
    # two writers interleaving read-concat-swap on the same table must
    # serialize: without the per-table append lock the later swap
    # discards the earlier acked batch (memory and WAL diverge)
    import threading

    c = Context()
    c.create_table("t", _base())
    n_threads, n_batches = 4, 8
    errors = []

    def writer(tid):
        try:
            for i in range(n_batches):
                c.append_rows("t", [(f"w{tid}b{i}", float(i))])
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    total = 2 + n_threads * n_batches
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == total
    # every acked batch is one whole WAL line — and replay agrees
    assert len(_wal_lines(ingest_root)) == n_threads * n_batches
    ingest._reset_for_tests()
    c2 = Context()
    c2.create_table("t", _base())
    got = c2.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == total


def test_wal_commit_point_fsyncs(ingest_root, monkeypatch):
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(ingest.os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd)))
    c = Context()
    c.create_table("t", _base())
    c.append_rows("t", [("c", 3.0)])
    assert synced  # durable-before-visible includes the fsync
    synced.clear()
    monkeypatch.setenv("DSQL_INGEST_FSYNC", "0")
    c.append_rows("t", [("d", 4.0)])
    assert not synced  # knob trades down to process-crash-only


def test_close_flushes_buffered_rows(ingest_root, monkeypatch):
    # rows acked BUFFERED must survive a graceful close/drain: close()
    # commits the buffer (WAL + apply) before the fds go away
    monkeypatch.setenv("DSQL_INGEST_BATCH_ROWS", "100")
    monkeypatch.setenv("DSQL_INGEST_BATCH_MS", "60000")
    c = Context()
    c.create_table("t", _base())
    assert c.append_rows("t", [("c", 3.0), ("d", 4.0)]) == 0  # buffered
    assert len(_wal_lines(ingest_root)) == 0
    c._ingest_log.close()
    assert len(_wal_lines(ingest_root)) == 1
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 4


def test_buffered_rows_hold_ledger_reservation(ingest_root, monkeypatch):
    # buffered rows occupy memory the broker must keep pricing: the
    # grant releases at flush time, not on the BUFFERED ack
    from dask_sql_tpu.runtime import scheduler
    monkeypatch.setenv("DSQL_INGEST_BATCH_ROWS", "100")
    monkeypatch.setenv("DSQL_INGEST_BATCH_MS", "60000")
    ledger = scheduler.get_manager().ledger
    c = Context()
    c.create_table("t", _base())
    r0 = ledger.reserved_bytes()
    assert c.append_rows("t", [("c", 3.0)]) == 0
    assert ledger.reserved_bytes() > r0
    assert c._ingest_log.flush_all() == 1
    assert ledger.reserved_bytes() == r0


def test_drop_table_truncates_wal(ingest_root):
    c = Context()
    c.create_table("t", _base())
    c.append_rows("t", [("c", 3.0)])
    assert len(_wal_lines(ingest_root)) == 1
    c.drop_table("t")
    assert len(_wal_lines(ingest_root)) == 0
    # a future table under the same name must not resurrect dropped rows
    ingest._reset_for_tests()
    c2 = Context()
    c2.create_table("t", _base())
    got = c2.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 2


def test_reregister_truncates_wal_no_double_apply(ingest_root):
    c = Context()
    c.create_table("t", _base())
    c.append_rows("t", [("c", 3.0)])
    # checkpoint: persist the current table and re-register it — the new
    # source carries the appended row, so the logged delta must go
    snapshot = c.sql("SELECT * FROM t", return_futures=False)
    c.create_table("t", snapshot)
    assert len(_wal_lines(ingest_root)) == 0
    got = c.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 3
    # restart replays nothing: the base alone is the table
    ingest._reset_for_tests()
    c2 = Context()
    c2.create_table("t", snapshot)
    got = c2.sql("SELECT COUNT(*) AS n FROM t", return_futures=False)
    assert int(got["n"][0]) == 3
