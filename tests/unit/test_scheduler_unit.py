"""Unit tests for runtime/scheduler.py: admission bounds, deadline-aware
rejection, deficit-weighted priority pick with aging, the memory ledger
(incl. the result cache's tenancy + pressure shrink), seats, and the
telemetry name-contract additions."""
import threading
import time

import numpy as np
import pytest

from dask_sql_tpu.runtime import faults
from dask_sql_tpu.runtime import resilience as res
from dask_sql_tpu.runtime import result_cache as rc
from dask_sql_tpu.runtime import scheduler as sched
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.table import Table


@pytest.fixture()
def mgr(monkeypatch):
    """A fresh manager: 1 slot, small queue, fast timeout, broker off."""
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "1")
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "2")
    monkeypatch.setenv("DSQL_QUEUE_TIMEOUT_MS", "60000")
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "0")
    return sched.WorkloadManager()


def _table(n_rows: int) -> Table:
    return Table.from_pydict({"a": np.zeros(n_rows, dtype=np.int64)})


def _counter_delta(fn, *names):
    before = {n: tel.REGISTRY.get(n) for n in names}
    fn()
    return {n: tel.REGISTRY.get(n) - before[n] for n in names}


# ---------------------------------------------------------------------------
# enable/disable + basic admission
# ---------------------------------------------------------------------------

def test_disabled_at_zero(monkeypatch):
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "0")
    m = sched.WorkloadManager()
    assert not m.enabled()
    assert m.claim_seat("interactive") is None
    with m.admission() as ticket:
        assert ticket is None


def test_immediate_admission_and_release(mgr):
    t = mgr.acquire("interactive", 0)
    assert t.admitted and mgr.running_count() == 1
    assert t.queued_ms is not None and t.queued_ms >= 0
    mgr.release(t)
    assert mgr.running_count() == 0
    # double release is a no-op
    mgr.release(t)
    assert mgr.running_count() == 0


def test_admission_counters_reconcile(mgr):
    def run():
        t = mgr.acquire("batch", 0)
        mgr.release(t)
    d = _counter_delta(run, "sched_admitted_batch", "sched_rejected_batch",
                       "sched_timeout_batch")
    assert d == {"sched_admitted_batch": 1, "sched_rejected_batch": 0,
                 "sched_timeout_batch": 0}


def test_queue_full_rejects(mgr):
    holder = mgr.acquire("interactive", 0)
    admitted = []

    def wait(i):
        t = mgr.acquire("interactive", 0)
        admitted.append(i)
        mgr.release(t)         # pass the slot on so every waiter drains

    threads = [threading.Thread(target=wait, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    deadline = time.time() + 5
    while mgr.queue_depth() < 2 and time.time() < deadline:
        time.sleep(0.01)
    assert mgr.queue_depth() == 2
    # slot busy + depth(2) full -> immediate typed rejection
    with pytest.raises(res.AdmissionRejected) as exc:
        mgr.acquire("interactive", 0)
    assert exc.value.retry_after_s >= 0
    assert exc.value.error_type == "INSUFFICIENT_RESOURCES"
    mgr.release(holder)
    for t in threads:
        t.join(timeout=5)
    assert sorted(admitted) == [0, 1]
    assert mgr.running_count() == 0


def test_queue_timeout(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_QUEUE_TIMEOUT_MS", "80")
    holder = mgr.acquire("interactive", 0)
    t0 = time.monotonic()
    with pytest.raises(res.AdmissionTimeout):
        mgr.acquire("interactive", 0)
    assert time.monotonic() - t0 < 5.0
    assert mgr.queue_depth() == 0        # the abandoned waiter left no ghost
    mgr.release(holder)


def test_timeout_counter_keeps_reconciliation(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_QUEUE_TIMEOUT_MS", "50")
    holder = mgr.acquire("background", 0)

    def run():
        with pytest.raises(res.AdmissionTimeout):
            mgr.acquire("background", 0)

    d = _counter_delta(run, "sched_timeout_background",
                       "sched_admitted_background")
    assert d["sched_timeout_background"] == 1
    assert d["sched_admitted_background"] == 0
    mgr.release(holder)


def test_deadline_expiry_rejects_before_enqueue(mgr):
    holder = mgr.acquire("interactive", 0)
    # seed the hold-time EWMA: the only admitted query "ran" ~10 s
    mgr._run_ewma_s = 10.0
    with res.query_scope(timeout_s=0.2):
        with pytest.raises(res.AdmissionRejected) as exc:
            mgr.acquire("interactive", 0)
    assert "deadline" in str(exc.value)
    mgr.release(holder)


def test_no_deadline_rejection_without_history(mgr, monkeypatch):
    """Without an EWMA there is no estimate — never reject on a guess; the
    queued wait itself still honours the deadline via resilience.check."""
    monkeypatch.setenv("DSQL_QUEUE_TIMEOUT_MS", "60000")
    holder = mgr.acquire("interactive", 0)
    assert mgr._run_ewma_s is None
    with res.query_scope(timeout_s=0.1):
        with pytest.raises(res.DeadlineExceeded):
            mgr.acquire("interactive", 0)
    mgr.release(holder)


def test_queued_wait_honors_cancellation(mgr):
    holder = mgr.acquire("interactive", 0)
    cancel = threading.Event()
    err = []

    def wait():
        try:
            with res.query_scope(cancel=cancel):
                mgr.acquire("interactive", 0)
        except BaseException as e:   # noqa: BLE001 - recording the verdict
            err.append(e)

    t = threading.Thread(target=wait)
    t.start()
    deadline = time.time() + 5
    while mgr.queue_depth() < 1 and time.time() < deadline:
        time.sleep(0.01)
    cancel.set()
    t.join(timeout=5)
    assert err and isinstance(err[0], res.QueryCancelled)
    mgr.release(holder)


# ---------------------------------------------------------------------------
# priority ordering + aging
# ---------------------------------------------------------------------------

def _run_contended(mgr, submissions):
    """Occupy the single slot, enqueue ``submissions`` [(priority, tag)],
    then release and record admission order."""
    holder = mgr.acquire("background", 0)
    order, lock = [], threading.Lock()

    def go(priority, tag):
        t = mgr.acquire(priority, 0)
        with lock:
            order.append(tag)
        time.sleep(0.01)
        mgr.release(t)

    threads = []
    for priority, tag in submissions:
        th = threading.Thread(target=go, args=(priority, tag))
        th.start()
        threads.append(th)
        # deterministic enqueue order
        deadline = time.time() + 5
        while mgr.queue_depth() < len(threads) and time.time() < deadline:
            time.sleep(0.005)
    mgr.release(holder)
    for th in threads:
        th.join(timeout=10)
    return order


def test_interactive_beats_batch(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "8")
    order = _run_contended(mgr, [("batch", "b1"), ("batch", "b2"),
                                 ("interactive", "i1"),
                                 ("interactive", "i2")])
    assert len(order) == 4
    # the first grant after the slot frees goes to the interactive class
    # even though both batch queries enqueued first
    assert order[0] == "i1"


def test_weighted_interleave_serves_both(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_QUEUE_DEPTH", "8")
    order = _run_contended(mgr, [("batch", "b1"), ("interactive", "i1"),
                                 ("batch", "b2"), ("interactive", "i2")])
    # deficit-weighted, not absolute: batch is served within the window,
    # not starved until interactive drains
    assert order.index("b1") < 3


def test_pick_is_starvation_free(mgr):
    """White-box DWRR check: under a standing interactive queue, the
    background head must still win within a bounded number of rounds
    (deficit carry + aging boost)."""
    now = time.monotonic()
    for _ in range(50):
        mgr._waiting["interactive"].append(
            sched.Ticket("interactive", 0, now))
    mgr._waiting["background"].append(sched.Ticket("background", 0, now))
    picks = [mgr._pick_locked() for _ in range(12)]
    assert "background" in picks
    # service is weighted: interactive dominates the window
    assert picks.count("interactive") > picks.count("background")
    for q in mgr._waiting.values():
        q.clear()


def test_aging_boost_promotes_old_waiter(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_QUEUE_AGING_MS", "100")
    now = time.monotonic()
    # a background query that has waited 2 s (20 aging units) outranks a
    # fresh interactive arrival (weight 8) on the very first pick
    mgr._waiting["background"].append(
        sched.Ticket("background", 0, now - 2.0))
    mgr._waiting["interactive"].append(
        sched.Ticket("interactive", 0, now))
    assert mgr._pick_locked() == "background"
    for q in mgr._waiting.values():
        q.clear()


# ---------------------------------------------------------------------------
# seats (the server's POST-time pre-claims)
# ---------------------------------------------------------------------------

def test_seat_claim_bounds_and_release(mgr):
    holder = mgr.acquire("interactive", 0)
    s1 = mgr.claim_seat("interactive")
    s2 = mgr.claim_seat("interactive")
    assert mgr.queue_depth() == 2
    # 1 running + 0 waiting + 2 seats == limit(1) + depth(2): full
    with pytest.raises(res.AdmissionRejected):
        mgr.claim_seat("interactive")
    mgr.release_seat(s1)
    assert mgr.queue_depth() == 1
    # releasing twice is a no-op
    mgr.release_seat(s1)
    assert mgr.queue_depth() == 1
    mgr.release_seat(s2)
    mgr.release(holder)


def test_seat_transfers_enqueue_timestamp(mgr):
    seat = mgr.claim_seat("batch")
    time.sleep(0.05)
    t = mgr.acquire("batch", 0, seat=seat)
    assert seat.consumed
    assert mgr.queue_depth() == 0
    # queue time is measured from the seat claim, not the acquire call
    assert t.queued_ms >= 40
    mgr.release(t)


# ---------------------------------------------------------------------------
# memory broker: ledger arithmetic + cache tenancy
# ---------------------------------------------------------------------------

def test_ledger_reserve_release(monkeypatch):
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "1")     # 1 MiB
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "0")
    ledger = sched.MemoryLedger(cache_fn=rc.ResultCache)
    got = ledger.reserve(512 * 1024)
    assert got == 512 * 1024
    # over-reservation fails (queues at the manager) instead of going
    # negative
    assert ledger.reserve(768 * 1024) is None
    ledger.release(got)
    assert ledger.reserved_bytes() == 0
    # estimates larger than the whole budget clamp so a lone query runs
    assert ledger.reserve(10 * 2**20) == 2**20
    ledger.release(2**20)


def test_ledger_disabled_at_zero(monkeypatch):
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "0")
    ledger = sched.MemoryLedger(cache_fn=rc.ResultCache)
    assert ledger.reserve(1 << 40) == 0      # admission-only mode
    assert ledger.reserved_bytes() == 0


def test_reservation_shrinks_cache_tenant(monkeypatch):
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "1")
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "1")
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "4")
    cache = rc.ResultCache()
    ledger = sched.MemoryLedger(cache_fn=lambda: cache)
    # ~0.75 MiB resident in the cache's device tier
    cache.put(rc.CacheKey("k1", ()), _table(48 * 1024))
    cache.put(rc.CacheKey("k2", ()), _table(48 * 1024))
    resident = cache.device_bytes
    assert resident > 512 * 1024
    # a 0.75 MiB reservation cannot fit next to it: the cache must spill
    got = ledger.reserve(768 * 1024)
    assert got == 768 * 1024
    assert cache.device_bytes <= 2**20 - 768 * 1024
    # the displaced entries moved to host, they were not destroyed
    assert cache.host_bytes > 0
    assert cache.get(rc.CacheKey("k1", ())) is not None
    ledger.release(got)
    cache.clear()


def test_shrink_device_to_drops_when_host_full(monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "4")
    monkeypatch.setenv("DSQL_RESULT_CACHE_HOST_MB", "0")
    cache = rc.ResultCache()
    cache.put(rc.CacheKey("k1", ()), _table(1024))
    assert cache.device_bytes > 0
    freed = cache.shrink_device_to(0)
    assert freed > 0
    assert cache.device_bytes == 0 and cache.host_bytes == 0


def test_cache_device_budget_is_ledger_tenant(monkeypatch):
    """With the global manager armed, the cache's effective device budget
    shrinks to the ledger headroom — but liveness (enabled) follows the
    BASE budget, so pressure never clears the whole cache."""
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "1")
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    cache = rc.ResultCache()
    mgr = sched.get_manager()
    assert cache.device_budget() == 2**20         # min(64 MiB, 1 MiB free)
    got = mgr.ledger.reserve(512 * 1024)
    try:
        assert cache.device_budget() == 512 * 1024
        assert cache.enabled()
    finally:
        mgr.ledger.release(got)


def test_over_reservation_queues_until_release(mgr, monkeypatch):
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    monkeypatch.setenv("DSQL_DEVICE_BUDGET_MB", "1")
    t1 = mgr.acquire("interactive", 800 * 1024)
    assert t1.reserved_bytes == 800 * 1024
    admitted = []

    def wait():
        # fits the slot count (2) but not the ledger: must queue, not crash
        t2 = mgr.acquire("interactive", 800 * 1024)
        admitted.append(t2)

    th = threading.Thread(target=wait)
    th.start()
    time.sleep(0.15)
    assert not admitted and mgr.queue_depth() == 1
    mgr.release(t1)                     # frees the ledger -> dispatch
    th.join(timeout=5)
    assert admitted and admitted[0].reserved_bytes == 800 * 1024
    mgr.release(admitted[0])


# ---------------------------------------------------------------------------
# working-set estimator + admission context manager
# ---------------------------------------------------------------------------

def test_estimate_plan_bytes_scales_with_operators():
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.sql.parser import parse_sql

    c = Context()
    c.create_table("t", pd.DataFrame({"a": np.arange(10_000),
                                      "b": np.arange(10_000) * 1.5}))

    def est(sql):
        plan = c._get_plan(parse_sql(sql)[0].query, sql)
        return sched.estimate_plan_bytes(plan, c)

    floor = sched._MIN_ESTIMATE
    scan = est("SELECT a, b FROM t") - floor
    agg = est("SELECT a, SUM(b) FROM t GROUP BY a") - floor
    join = est("SELECT x.a FROM t x, t y WHERE x.a = y.a") - floor
    assert scan >= 10_000 * 16
    assert agg > scan            # aggregate multiplier
    assert join > 2 * scan       # two scans x join multiplier


def test_admission_nested_rides_outer_slot(mgr):
    with mgr.admission(priority="interactive") as outer:
        assert outer is not None
        assert mgr.running_count() == 1
        with mgr.admission(priority="interactive") as inner:
            assert inner is None          # nested plan: no second slot
            assert mgr.running_count() == 1
    assert mgr.running_count() == 0


def test_admission_fault_site(mgr):
    with faults.inject("admission:1"):
        with pytest.raises(faults.FaultInjected):
            with mgr.admission(priority="batch"):
                pass  # pragma: no cover - admission raised
    # the fault consumed no slot and the next admission works
    assert mgr.running_count() == 0 and mgr.queue_depth() == 0
    with mgr.admission(priority="batch") as t:
        assert t is not None


# ---------------------------------------------------------------------------
# telemetry contract additions
# ---------------------------------------------------------------------------

def test_sched_names_in_stable_contract():
    for name in ("sched_admitted_interactive", "sched_admitted_batch",
                 "sched_admitted_background", "sched_rejected_interactive",
                 "sched_rejected_batch", "sched_rejected_background",
                 "sched_timeout_interactive", "sched_timeout_batch",
                 "sched_timeout_background", "fault_admission",
                 "server_throttled"):
        assert name in tel.STABLE_COUNTERS
    for name in ("sched_queue_depth", "sched_running",
                 "sched_reserved_bytes"):
        assert name in tel.STABLE_GAUGES


def test_gauges_track_queue_and_running(mgr):
    t = mgr.acquire("interactive", 0)
    assert tel.REGISTRY.get_gauge("sched_running") == 1
    mgr.release(t)
    assert tel.REGISTRY.get_gauge("sched_running") == 0


# ---------------------------------------------------------------------------
# honest hold-time EWMA: retry/backoff sleep must not inflate the
# queue-wait estimate (and thereby trigger spurious deadline fast-rejects)
# ---------------------------------------------------------------------------

def test_release_subtracts_recorded_backoff(mgr):
    t = mgr.acquire("interactive", 0)
    time.sleep(0.05)
    # pretend nearly the whole hold was retry-backoff sleep
    t.backoff_s = 10.0
    mgr.release(t)
    assert mgr._run_ewma_s is not None
    assert mgr._run_ewma_s < 0.05, (
        f"EWMA {mgr._run_ewma_s} still counts backoff sleep")


def test_admission_threads_runtime_backoff_into_ewma(mgr, monkeypatch):
    """End-to-end through the real path: an in-rung retry backoff inside
    an admitted query's scope is recorded on the QueryRuntime
    (resilience.backoff) and subtracted at release."""
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "150")
    with res.query_scope():
        with mgr.admission(priority="interactive") as t:
            assert t is not None
            res.backoff(1, "test-site")       # ~150 ms asleep in the slot
    assert mgr._run_ewma_s is not None
    assert mgr._run_ewma_s < 0.1, (
        f"EWMA {mgr._run_ewma_s} inflated by retry backoff")


def test_backoff_outside_admission_does_not_leak(mgr, monkeypatch):
    """Backoff spent BEFORE admission (e.g. while a previous statement of
    the same query retried) must not be charged to this slot."""
    monkeypatch.setenv("DSQL_RETRY_BASE_MS", "80")
    with res.query_scope():
        res.backoff(1, "pre-admission")
        with mgr.admission(priority="batch") as t:
            time.sleep(0.05)
            assert t is not None
    # hold was ~50 ms of real work; pre-admission backoff not subtracted
    assert 0.02 < mgr._run_ewma_s < 0.5


# ---------------------------------------------------------------------------
# drain mode
# ---------------------------------------------------------------------------

def test_drain_rejects_new_admissions_typed(mgr):
    mgr.begin_drain()
    try:
        assert mgr.draining()
        assert tel.REGISTRY.get_gauge("server_draining") == 1
        with pytest.raises(res.ServerDraining) as exc:
            mgr.acquire("interactive", 0)
        assert exc.value.retry_after_s > 0
        with pytest.raises(res.ServerDraining):
            mgr.claim_seat("batch")
    finally:
        mgr.end_drain()
    assert not mgr.draining()
    assert tel.REGISTRY.get_gauge("server_draining") == 0
    # back to normal service
    t = mgr.acquire("interactive", 0)
    mgr.release(t)


def test_drain_rejections_reconcile_counters(mgr):
    mgr.begin_drain()
    try:
        def run():
            with pytest.raises(res.ServerDraining):
                mgr.acquire("background", 0)
        d = _counter_delta(run, "sched_rejected_background",
                           "sched_admitted_background")
        assert d["sched_rejected_background"] == 1
        assert d["sched_admitted_background"] == 0
    finally:
        mgr.end_drain()


def test_inflight_query_survives_drain(mgr):
    """Draining refuses NEW work; an already-admitted query keeps its slot
    and releases normally."""
    t = mgr.acquire("interactive", 0)
    mgr.begin_drain()
    try:
        assert mgr.running_count() == 1
        with pytest.raises(res.ServerDraining):
            mgr.acquire("interactive", 0)
        mgr.release(t)
        assert mgr.running_count() == 0
    finally:
        mgr.end_drain()


def test_drain_independent_of_enabled(monkeypatch):
    """A draining process refuses new work even with the scheduler
    subsystem off (the server's POST gate relies on this)."""
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "0")
    m = sched.WorkloadManager()
    m.begin_drain()
    try:
        assert m.draining()
        with pytest.raises(res.ServerDraining):
            m.claim_seat("interactive")
    finally:
        m.end_drain()
