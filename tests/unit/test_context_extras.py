"""Context auxiliaries: profiling trace, explain, visualize."""
import os

import pandas as pd

from dask_sql_tpu import Context


def test_profile_writes_trace(tmp_path):
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3]}))
    out = c.profile("SELECT SUM(a) AS s FROM t", trace_dir=str(tmp_path))
    assert out.to_pandas()["s"][0] == 6
    # at least one profiler artifact lands in the directory tree
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found


def test_visualize_writes_plan(tmp_path, ):
    c = Context()
    c.create_table("t", pd.DataFrame({"a": [1, 2, 3]}))
    path = tmp_path / "plan.png"
    text = c.visualize("SELECT a FROM t WHERE a > 1", str(path))
    assert "LogicalTableScan" in text
    assert (tmp_path / "plan.txt").exists()
