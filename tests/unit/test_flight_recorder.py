"""Flight recorder failure modes (runtime/flight_recorder.py): ring
truncation, corrupt-line tolerance, cross-process appends, the EWMA
statistics history, and the zero-overhead disabled path."""
import json
import os
import subprocess
import sys

import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import flight_recorder as fr
from dask_sql_tpu.runtime import telemetry as tel


@pytest.fixture()
def hist(tmp_path, monkeypatch):
    path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("DSQL_HISTORY_FILE", path)
    return path


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------

def test_append_and_read_roundtrip(hist):
    fr._append(hist, {"kind": "query", "i": 1})
    fr._append(hist, {"kind": "stage", "i": 2})
    fr._append(hist, {"kind": "query", "i": 3})
    assert [e["i"] for e in fr.read_events()] == [1, 2, 3]
    assert [e["i"] for e in fr.read_events(kind="query")] == [1, 3]
    assert [e["i"] for e in fr.read_events(kind="query", limit=1)] == [3]


def test_ring_truncates_at_limit(hist, monkeypatch):
    # fractional DSQL_HISTORY_MB; the floor clamps to 4096 bytes
    monkeypatch.setenv("DSQL_HISTORY_MB", "0.001")
    assert fr.history_limit_bytes() == 4096
    before = tel.REGISTRY.get("history_truncations")
    pad = "x" * 100
    for i in range(100):
        fr._append(hist, {"kind": "query", "i": i, "pad": pad})
    assert os.path.getsize(hist) <= 4096
    assert tel.REGISTRY.get("history_truncations") > before
    events = fr.read_events()
    assert events, "ring kept SOME history"
    # the ring keeps the NEWEST records and drops the oldest
    assert events[-1]["i"] == 99
    assert events[0]["i"] > 0
    assert [e["i"] for e in events] == sorted(e["i"] for e in events)


def test_history_limit_parsing(monkeypatch):
    monkeypatch.delenv("DSQL_HISTORY_MB", raising=False)
    assert fr.history_limit_bytes() == 16 * 2**20
    monkeypatch.setenv("DSQL_HISTORY_MB", "2")
    assert fr.history_limit_bytes() == 2 * 2**20
    monkeypatch.setenv("DSQL_HISTORY_MB", "not-a-number")
    assert fr.history_limit_bytes() == 16 * 2**20


def test_corrupt_lines_are_skipped(hist):
    fr._append(hist, {"kind": "query", "i": 1})
    with open(hist, "ab") as f:
        f.write(b"this is not json\n")
        f.write(b'{"kind": "query", "torn": tru')  # torn mid-write
        f.write(b"\n[1, 2, 3]\n")                  # json but not a dict
    fr._append(hist, {"kind": "query", "i": 2})
    assert [e["i"] for e in fr.read_events()] == [1, 2]


def test_missing_file_reads_empty(hist):
    assert fr.read_events() == []


def test_disabled_reads_empty(monkeypatch):
    monkeypatch.delenv("DSQL_HISTORY_FILE", raising=False)
    assert fr.read_events() == []
    assert fr.history_path() is None
    assert not fr.enabled()


def test_concurrent_appends_from_two_processes(hist, monkeypatch):
    monkeypatch.setenv("DSQL_HISTORY_MB", "10")
    code = (
        "import os\n"
        "from dask_sql_tpu.runtime import flight_recorder as fr\n"
        "p = os.environ['DSQL_HISTORY_FILE']\n"
        "tag = os.environ['FR_TAG']\n"
        "for i in range(150):\n"
        "    fr._append(p, {'kind': 'query', 'tag': tag, 'i': i})\n"
    )
    procs = []
    for tag in ("a", "b"):
        env = dict(os.environ, FR_TAG=tag, JAX_PLATFORMS="cpu")
        procs.append(subprocess.Popen([sys.executable, "-c", code], env=env,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE))
    for p in procs:
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, err.decode()
    # every line parses (O_APPEND single-write atomicity: interleaved
    # writers can never tear each other's lines)
    with open(hist, "rb") as f:
        lines = f.readlines()
    events = [json.loads(raw) for raw in lines]
    assert len(events) == 300
    for tag in ("a", "b"):
        seen = [e["i"] for e in events if e["tag"] == tag]
        assert seen == list(range(150))  # per-writer order preserved


# ---------------------------------------------------------------------------
# EWMA statistics history
# ---------------------------------------------------------------------------

def test_ewma_stats_fold(hist):
    fr._observe_stat("fp1", nbytes=1000, rows=10, ms=5.0)
    e = fr.get_stats("fp1")
    assert e["bytes"] == 1000.0 and e["rows"] == 10.0 and e["n"] == 1
    fr._observe_stat("fp1", nbytes=2000)
    e = fr.get_stats("fp1")
    assert e["bytes"] == pytest.approx(0.3 * 2000 + 0.7 * 1000)
    assert e["rows"] == 10.0  # untouched fields keep their EWMA
    assert e["n"] == 2
    assert fr.get_stats("missing") is None


def test_plan_history_bytes_headroom(hist, monkeypatch):
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    from dask_sql_tpu.sql.parser import parse_sql
    plan = c._get_plan(parse_sql("SELECT SUM(a) AS s FROM t")[0].query)
    fp = fr.plan_fingerprint(plan, c)
    assert fp is not None
    assert fr.plan_history_bytes(plan, c) is None  # never measured
    fr._observe_stat(fp, nbytes=1000)
    assert fr.plan_history_bytes(plan, c) == 1500  # default 1.5x headroom
    monkeypatch.setenv("DSQL_HISTORY_HEADROOM", "2.0")
    assert fr.plan_history_bytes(plan, c) == 2000
    monkeypatch.setenv("DSQL_HISTORY_HEADROOM", "0.5")
    assert fr.plan_history_bytes(plan, c) == 1000  # clamped to >= 1.0


def test_stats_sidecar_bounded_under_churn(hist, monkeypatch):
    """The EWMA sidecar must not grow without bound as ad-hoc plans churn
    unique fingerprints: ring truncation prunes entries past the TTL and
    caps survivors to DSQL_HISTORY_STATS_MAX newest-by-updated."""
    monkeypatch.setenv("DSQL_HISTORY_MB", "0.001")     # truncate often
    monkeypatch.setenv("DSQL_HISTORY_STATS_MAX", "20")
    cap = fr.stats_max_entries()
    assert cap == 20
    pad = "x" * 120
    for i in range(200):                # 200 one-off fingerprints
        fr._observe_stat(f"churn-fp-{i}", nbytes=1000 + i)
        fr._append(hist, {"kind": "query", "i": i, "pad": pad})
    stats = fr._STATS.read()
    # bounded: prune rides truncation cadence, so between truncations at
    # most one ring-half of fresh observations sits past the cap — far
    # below the 200 fingerprints churned
    per_cycle = fr.history_limit_bytes() // 2 // 120
    assert len(stats) <= cap + per_cycle
    fr._prune_stats()
    assert len(fr._STATS.read()) <= cap
    # newest-by-updated win
    assert "churn-fp-199" in fr._STATS.read()
    assert "churn-fp-0" not in stats


def test_stats_sidecar_ttl_prune(hist, monkeypatch):
    monkeypatch.setenv("DSQL_HISTORY_STATS_TTL_S", "60")
    fr._observe_stat("fresh-fp", nbytes=100)
    stale = dict(fr._STATS.read())
    stale["stale-fp"] = {"bytes": 1.0, "n": 1,
                         "updated": __import__("time").time() - 3600}
    stale["no-timestamp-fp"] = {"bytes": 1.0, "n": 1}
    fr._STATS.write(stale)
    fr._prune_stats()
    stats = fr._STATS.read()
    assert "fresh-fp" in stats
    assert "stale-fp" not in stats          # past the TTL
    assert "no-timestamp-fp" not in stats   # no updated => prunable
    # default TTL parses and floors sanely
    monkeypatch.delenv("DSQL_HISTORY_STATS_TTL_S", raising=False)
    assert fr.stats_ttl_s() == 7 * 86400.0
    monkeypatch.setenv("DSQL_HISTORY_STATS_TTL_S", "junk")
    assert fr.stats_ttl_s() == 7 * 86400.0


# ---------------------------------------------------------------------------
# recording through real queries
# ---------------------------------------------------------------------------

def test_query_envelope_recorded(hist):
    c = Context()
    c.create_table("t", {"a": [1, 2, 3], "b": [1.0, 2.0, 3.0]})
    c.sql("SELECT a, SUM(b) AS s FROM t GROUP BY a")
    events = fr.read_events(kind="query")
    assert len(events) == 1
    e = events[0]
    assert e["outcome"] == "ok" and e["error"] == ""
    assert e["query"].startswith("SELECT a, SUM(b)")
    assert e["pid"] == os.getpid()
    assert e["rows_out"] == 3
    assert e["plan_fp"]
    assert e["wall_ms"] > 0
    # the plan-level EWMA entry fed from the envelope
    assert fr.get_stats(e["plan_fp"])["n"] == 1


def test_error_envelope_recorded(hist):
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    with pytest.raises(Exception):
        c.sql("SELECT nosuchcolumn FROM t")
    events = fr.read_events(kind="query")
    assert len(events) == 1
    assert events[0]["outcome"] == "error"
    assert events[0]["error"] != ""


def test_cross_process_history_via_system_queries(hist):
    """A FRESH interpreter's queries land in the ring; this process then
    reads them through SQL (the acceptance-criteria proof)."""
    code = (
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3]})\n"
        "c.sql('SELECT SUM(a) AS s FROM t')\n"
        "c.sql('SELECT COUNT(*) AS n FROM t')\n"
        "c.sql('SELECT MAX(a) AS m FROM t')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", DSQL_TIERED="0",
               DSQL_MAX_CONCURRENT_QUERIES="0", DSQL_RESULT_CACHE_MB="0")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=300)
    assert proc.returncode == 0, proc.stderr.decode()

    child_events = fr.read_events(kind="query")
    assert len(child_events) == 3
    assert all(e["pid"] != os.getpid() for e in child_events)

    c = Context()  # fresh context, no tables — only the system schema
    rows = c.sql("SELECT count(*) AS n FROM system.queries").to_pylist()
    assert rows[0][0] >= 3
    pids = c.sql("SELECT DISTINCT pid FROM system.queries").to_pylist()
    assert any(p[0] != os.getpid() for p in pids)


# ---------------------------------------------------------------------------
# the zero-overhead disabled path
# ---------------------------------------------------------------------------

class _Tripwire:
    """A context manager / callable that fails the test when touched."""

    def __enter__(self):
        raise AssertionError("disabled path touched the recorder lock")

    def __exit__(self, *a):
        return False

    def __call__(self, *a, **k):
        raise AssertionError("disabled path called into the recorder")


def test_disabled_path_touches_nothing(monkeypatch):
    """With DSQL_HISTORY_FILE unset the hot path must not take the
    recorder's lock, append, observe stats, or register live traces —
    every hook is a single env lookup returning early."""
    monkeypatch.delenv("DSQL_HISTORY_FILE", raising=False)
    monkeypatch.setattr(fr, "_LOCK", _Tripwire())
    monkeypatch.setattr(fr, "_append", _Tripwire())
    monkeypatch.setattr(fr, "_observe_stat", _Tripwire())
    monkeypatch.setattr(fr, "begin_query", _Tripwire())
    before = tel.REGISTRY.get("history_records")
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    out = c.sql("SELECT SUM(a) AS s FROM t")
    assert out.to_pylist() == [[6]]
    assert fr._ACTIVE == {}
    assert tel.REGISTRY.get("history_records") == before
