"""Unit tests for delta-based view maintenance (runtime/delta.py): the
join/COUNT(DISTINCT) analyzers and their O(delta) refresh paths, oracle-
checked against full recomputes (ISSUE 20)."""
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import matview as mv
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture(autouse=True)
def _cache_on(monkeypatch):
    # maintained view state (agg partials, cdistinct refcounts) is a
    # result-cache tenant; keep the cache alive for these suites
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    yield


def _ctx():
    c = Context()
    c.create_table("t1", pd.DataFrame({
        "k": ["a", "b", "c"], "x": [1.0, 2.0, 3.0]}))
    c.create_table("t2", pd.DataFrame({
        "k": ["a", "a", "b"], "y": [10, 20, 30]}))
    return c


def _shape_of(c, sql):
    plan = c._get_plan(parse_sql(sql)[0].query, sql)
    return mv._analyze(plan, c)


def _oracle(c, view_sql, view_name):
    got = c.sql(f"SELECT * FROM {view_name}", return_futures=False)
    want = c.sql(view_sql, return_futures=False)
    cols = sorted(got.columns)
    got = got[cols].sort_values(cols).reset_index(drop=True)
    want = want[cols].sort_values(cols).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, want, check_dtype=False)


# ---------------------------------------------------------------------------
# analyzer verdicts
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("query,kind", [
    ("SELECT a.k, a.x, b.y FROM t1 a INNER JOIN t2 b ON a.k = b.k",
     "join"),
    ("SELECT a.k FROM t1 a, t1 b WHERE a.k = b.k", "join"),  # self-join
    ("SELECT a.x, b.y FROM t1 a JOIN t2 b ON a.k = b.k "
     "WHERE b.y > 5", "join"),
    ("SELECT COUNT(DISTINCT k) AS n FROM t1", "cdistinct"),
    ("SELECT k, COUNT(DISTINCT y) AS n FROM t2 GROUP BY k", "cdistinct"),
    # plain DISTINCT lowers to a group-by: stays on the agg path
    ("SELECT DISTINCT k FROM t2", "agg"),
])
def test_analyze_maintainable_shapes(query, kind):
    c = _ctx()
    shape, reason = _shape_of(c, query)
    assert shape is not None, reason
    assert shape.kind == kind


@pytest.mark.parametrize("query,needle", [
    ("SELECT a.k, SUM(b.y) AS s FROM t1 a JOIN t2 b ON a.k = b.k "
     "GROUP BY a.k", "aggregates over joins"),
    ("SELECT a.x, b.y FROM t1 a JOIN t2 b ON a.k = b.k "
     "ORDER BY b.y LIMIT 2", "ORDER BY"),
    ("SELECT COUNT(DISTINCT k) AS n, COUNT(*) AS m FROM t1", "DISTINCT"),
])
def test_analyze_refusals_keep_reason(query, needle):
    c = _ctx()
    shape, reason = _shape_of(c, query)
    assert shape is None
    assert needle.lower() in reason.lower()


# ---------------------------------------------------------------------------
# delta-join refresh, oracle-checked
# ---------------------------------------------------------------------------

VIEW_JOIN = ("SELECT a.k AS k, a.x AS x, b.y AS y "
             "FROM t1 a INNER JOIN t2 b ON a.k = b.k")


def test_join_view_maintains_across_appends():
    c = _ctx()
    c.sql(f"CREATE MATERIALIZED VIEW vj AS {VIEW_JOIN}")
    inc0 = tel.REGISTRY.get("mv_refresh_incremental", 0)
    full0 = tel.REGISTRY.get("mv_refresh_full", 0)
    # left side only
    c.append_rows("t1", [("a", 4.0), ("z", 5.0)])
    _oracle(c, VIEW_JOIN, "vj")
    # right side only
    c.append_rows("t2", [("c", 40), ("a", 50)])
    _oracle(c, VIEW_JOIN, "vj")
    # both sides pending in one refresh (the cross term ΔA⋈ΔB matters:
    # the appended t1 'q' row only matches the appended t2 'q' row)
    c.append_rows("t1", [("q", 6.0)])
    c.append_rows("t2", [("q", 60)])
    _oracle(c, VIEW_JOIN, "vj")
    assert tel.REGISTRY.get("mv_refresh_incremental", 0) == inc0 + 3
    assert tel.REGISTRY.get("mv_refresh_full", 0) == full0


def test_self_join_view_maintains():
    view = ("SELECT a.k AS k, a.x AS xa, b.x AS xb "
            "FROM t1 a, t1 b WHERE a.k = b.k")
    c = _ctx()
    c.sql(f"CREATE MATERIALIZED VIEW vs AS {view}")
    inc0 = tel.REGISTRY.get("mv_refresh_incremental", 0)
    # an appended row must join against itself AND the old prefix
    c.append_rows("t1", [("a", 9.0)])
    _oracle(c, view, "vs")
    assert tel.REGISTRY.get("mv_refresh_incremental", 0) == inc0 + 1


def test_join_view_filter_below_join_maintains():
    view = ("SELECT a.k AS k, b.y AS y FROM t1 a "
            "INNER JOIN t2 b ON a.k = b.k WHERE b.y > 15")
    c = _ctx()
    c.sql(f"CREATE MATERIALIZED VIEW vf AS {view}")
    c.append_rows("t2", [("b", 5), ("b", 99)])  # one filtered, one kept
    _oracle(c, view, "vf")


# ---------------------------------------------------------------------------
# COUNT(DISTINCT) refresh (refcounted value state), oracle-checked
# ---------------------------------------------------------------------------

def test_cdistinct_global_maintains():
    view = "SELECT COUNT(DISTINCT k) AS n FROM t2"
    c = _ctx()
    c.sql(f"CREATE MATERIALIZED VIEW vd AS {view}")
    inc0 = tel.REGISTRY.get("mv_refresh_incremental", 0)
    c.append_rows("t2", [("a", 70)])  # duplicate value: count unchanged
    got = c.sql("SELECT n FROM vd", return_futures=False)
    assert int(got["n"][0]) == 2
    c.append_rows("t2", [("z", 80), ("z", 90)])  # one new distinct value
    got = c.sql("SELECT n FROM vd", return_futures=False)
    assert int(got["n"][0]) == 3
    assert tel.REGISTRY.get("mv_refresh_incremental", 0) == inc0 + 2


def test_cdistinct_grouped_maintains_and_skips_nulls():
    view = "SELECT k, COUNT(DISTINCT y) AS n FROM t2 GROUP BY k"
    c = _ctx()
    c.sql(f"CREATE MATERIALIZED VIEW vg AS {view}")
    # duplicate value in 'a', new value in 'b', brand-new group 'c',
    # and a NULL (COUNT(DISTINCT) never counts NULL)
    c.append_rows("t2", [("a", 10), ("b", 31), ("c", 1), ("c", None)])
    _oracle(c, view, "vg")
    got = c.sql("SELECT n FROM vg WHERE k = 'c'", return_futures=False)
    assert int(got["n"][0]) == 1


# ---------------------------------------------------------------------------
# staleness surfacing (system.matviews)
# ---------------------------------------------------------------------------

def test_staleness_columns_track_pending_deltas():
    c = _ctx()
    c.sql("CREATE MATERIALIZED VIEW vp AS SELECT k, SUM(x) AS s FROM t1 "
          "GROUP BY k")
    c.append_rows("t1", [("a", 1.0), ("b", 1.0)])
    rows = c.sql("SELECT pending_rows, staleness_s FROM system.matviews "
                 "WHERE name = 'vp'", return_futures=False)
    assert int(rows["pending_rows"][0]) == 2
    assert float(rows["staleness_s"][0]) >= 0.0
    c.sql("SELECT * FROM vp", return_futures=False)  # refresh drains
    rows = c.sql("SELECT pending_rows FROM system.matviews "
                 "WHERE name = 'vp'", return_futures=False)
    assert int(rows["pending_rows"][0]) == 0
    assert tel.REGISTRY.gauges().get("mv_pending_rows", -1) == 0
