"""Fugue integration gating: module imports without fugue and raises a clear
error on use (reference integrations/fugue.py surface)."""
import pytest

from dask_sql_tpu.integrations import fugue as fg


def test_surface_exists():
    assert hasattr(fg, "TpuSQLEngine")
    assert hasattr(fg, "TpuSQLExecutionEngine")
    assert hasattr(fg, "fsql_tpu")


def test_gated_without_fugue():
    if fg._HAS_FUGUE:
        pytest.skip("fugue installed; gating not applicable")
    with pytest.raises(ImportError, match="fugue"):
        fg.fsql_tpu("SELECT 1")
    with pytest.raises(ImportError, match="fugue"):
        fg.TpuSQLEngine()
