"""The estimate feedback loop (scheduler.estimate_working_set +
flight_recorder.plan_history_bytes): first run is the scan-bytes
heuristic, repeat runs reserve from measured history."""
import numpy as np
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import flight_recorder as fr
from dask_sql_tpu.runtime import scheduler as sched
from dask_sql_tpu.runtime import telemetry as tel
from dask_sql_tpu.sql.parser import parse_sql


@pytest.fixture()
def hist(tmp_path, monkeypatch):
    # module name carries "scheduler", so the conftest pin leaves the
    # workload manager ON; arm a small concurrency limit explicitly
    monkeypatch.setenv("DSQL_MAX_CONCURRENT_QUERIES", "2")
    path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("DSQL_HISTORY_FILE", path)
    return path


def test_estimate_from_history_on_repeat_run(hist):
    c = Context()
    c.create_table("t", {"a": np.arange(64, dtype=np.int64),
                         "b": np.arange(64, dtype=np.float64)})
    sql = "SELECT a, SUM(b) AS s FROM t GROUP BY a"

    before = tel.REGISTRY.get("estimate_from_history")
    c.sql(sql)
    # first run had no history: the heuristic answered
    assert tel.REGISTRY.get("estimate_from_history") == before
    ev1 = fr.read_events(kind="query")[-1]
    assert ev1["est_source"] == "heuristic"
    assert ev1["measured_bytes"] > 0

    c.sql(sql)
    assert tel.REGISTRY.get("estimate_from_history") == before + 1
    ev2 = fr.read_events(kind="query")[-1]
    assert ev2["est_source"] == "history"
    # the measured reservation is far tighter than the scan-bytes guess
    assert ev2["est_bytes"] < ev1["est_bytes"]
    assert ev2["est_bytes"] >= ev2["measured_bytes"]  # headroom holds


def test_estimate_working_set_sources(hist):
    c = Context()
    c.create_table("t", {"a": np.arange(32, dtype=np.int64)})
    plan = c._get_plan(parse_sql("SELECT SUM(a) AS s FROM t")[0].query)

    est, src = sched.estimate_working_set(plan, c)
    assert src == "heuristic"
    assert est == sched.estimate_plan_bytes(plan, c)

    fp = fr.plan_fingerprint(plan, c)
    fr._observe_stat(fp, nbytes=10 * 2**20)
    est2, src2 = sched.estimate_working_set(plan, c)
    assert src2 == "history"
    assert est2 == 15 * 2**20  # 10 MiB EWMA x 1.5 headroom


def test_heuristic_when_recorder_disabled(monkeypatch):
    monkeypatch.delenv("DSQL_HISTORY_FILE", raising=False)
    c = Context()
    c.create_table("t", {"a": np.arange(32, dtype=np.int64)})
    plan = c._get_plan(parse_sql("SELECT SUM(a) AS s FROM t")[0].query)
    _est, src = sched.estimate_working_set(plan, c)
    assert src == "heuristic"
