"""The read-only ``system`` schema (runtime/system_tables.py +
Context._resolve_system_table): lazy resolution, fixed schemas at zero
rows, the LIVE system.active view, result-cache exemption, and
user-schema shadowing."""
import threading
import time

import numpy as np
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import flight_recorder as fr
from dask_sql_tpu.runtime import telemetry as tel

ALL_TABLES = ("queries", "active", "metrics", "cache", "quarantine",
              "programs")


@pytest.fixture()
def hist(tmp_path, monkeypatch):
    path = str(tmp_path / "hist.jsonl")
    monkeypatch.setenv("DSQL_HISTORY_FILE", path)
    return path


def test_all_tables_bind_and_execute_when_empty(hist):
    c = Context()  # no user tables at all
    for t in ALL_TABLES:
        out = c.sql(f"SELECT * FROM system.{t}")
        assert out.num_columns > 0, t


def test_all_tables_bind_without_recorder(monkeypatch):
    monkeypatch.delenv("DSQL_HISTORY_FILE", raising=False)
    c = Context()
    for t in ALL_TABLES:
        out = c.sql(f"SELECT * FROM system.{t}")
        assert out.num_columns > 0, t
    # no history file: the queries view is simply empty
    assert c.sql("SELECT count(*) AS n FROM system.queries"
                 ).to_pylist() == [[0]]


def test_queries_reflects_executed_queries(hist):
    c = Context()
    c.create_table("t", {"a": [1, 2, 3]})
    c.sql("SELECT SUM(a) AS s FROM t")
    rows = c.sql("SELECT query, outcome, rows_out FROM system.queries"
                 ).to_pylist()
    assert ["SELECT SUM(a) AS s FROM t", "ok", 1] in rows


def test_metrics_table_carries_registry(hist):
    c = Context()
    rows = c.sql("SELECT name, kind, value FROM system.metrics").to_pylist()
    names = {r[0] for r in rows}
    assert "queries" in names and "history_records" in names
    assert {r[1] for r in rows} <= {"counter", "gauge"}


def test_system_reads_are_never_cached(hist, monkeypatch):
    monkeypatch.setenv("DSQL_RESULT_CACHE_MB", "64")
    c = Context()
    before_h = tel.REGISTRY.get("result_cache_hits")
    n1 = c.sql("SELECT count(*) AS n FROM system.queries").to_pylist()[0][0]
    n2 = c.sql("SELECT count(*) AS n FROM system.queries").to_pylist()[0][0]
    # the first count(*) recorded its own envelope, so an UNCACHED second
    # read must see one more row; a (stale) cache hit would repeat n1
    assert n2 == n1 + 1
    assert tel.REGISTRY.get("result_cache_hits") == before_h


def test_plan_key_is_volatile_for_system_scans(hist):
    from dask_sql_tpu.runtime import result_cache as _rc
    from dask_sql_tpu.sql.parser import parse_sql

    c = Context()
    plan = c._get_plan(parse_sql("SELECT * FROM system.metrics")[0].query)
    text, volatile, _scans = _rc.canonical_plan(plan, c)
    assert volatile


def test_user_schema_named_system_shadows_builtin(hist):
    c = Context()
    c.sql("CREATE SCHEMA system")
    with pytest.raises(Exception):
        c.sql("SELECT * FROM system.queries")
    c.sql("DROP SCHEMA system")
    assert c.sql("SELECT * FROM system.metrics").num_rows > 0


def test_active_reflects_live_query(hist):
    """system.active must show a query WHILE it runs (live view, not a
    snapshot fixture): a sleeping vectorized UDF holds one query open in a
    worker thread while the main thread polls through SQL."""
    c = Context()
    c.create_table("t", {"a": np.arange(8, dtype=np.int64)})
    release = threading.Event()

    def slow_fn(x):
        release.set()
        time.sleep(1.5)
        return x.astype(np.float64)

    c.register_function(slow_fn, "slow_fn", [("x", np.int64)], np.float64)
    result = {}

    def run():
        result["table"] = c.sql(
            "SELECT SUM(slow_fn(a)) AS s FROM t").to_pylist()

    worker = threading.Thread(target=run)
    worker.start()
    try:
        assert release.wait(timeout=60), "UDF never started"
        rows = c.sql("SELECT state, query, phase FROM system.active"
                     ).to_pylist()
        running = [r for r in rows if "slow_fn" in r[1]]
        assert running, f"live query not visible in system.active: {rows}"
        assert running[0][0] == "running"
    finally:
        worker.join(timeout=60)
    assert result["table"] == [[28.0]]
    # after completion the live registry is drained again
    rows = c.sql("SELECT query FROM system.active").to_pylist()
    assert not any("slow_fn" in r[0] for r in rows)
    assert len(fr._ACTIVE) <= 1  # only the poll itself may still be open


def test_unknown_system_table_errors(hist):
    c = Context()
    with pytest.raises(Exception):
        c.sql("SELECT * FROM system.nosuchtable")
