"""Parser unit tests (reference: tests/unit/test_utils.py error formatting +
grammar coverage implied by integration suite)."""
import pytest

from dask_sql_tpu.sql import ast as A
from dask_sql_tpu.sql.lexer import tokenize
from dask_sql_tpu.sql.parser import parse_one, parse_sql
from dask_sql_tpu.utils import ParsingException


def test_tokenize_basic():
    toks = tokenize("SELECT a, 'str''ing', 1.5e3 FROM \"T\"")
    kinds = [t.kind for t in toks]
    assert kinds == ["IDENT", "IDENT", "OP", "STRING", "OP", "NUMBER",
                     "IDENT", "QIDENT", "EOF"]
    assert toks[3].text == "str'ing"


def test_tokenize_comments():
    toks = tokenize("SELECT 1 -- comment\n + /* block */ 2")
    assert [t.text for t in toks if t.kind != "EOF"] == ["SELECT", "1", "+", "2"]


def test_parse_select():
    stmt = parse_one("SELECT a, b AS c FROM t WHERE a > 1")
    assert isinstance(stmt, A.QueryStatement)
    q = stmt.query
    assert len(q.projections) == 2
    assert q.projections[1][1] == "c"
    assert q.where is not None


def test_parse_error_position():
    with pytest.raises(ParsingException) as exc:
        parse_one("SELECT FROM FROM t")
    assert "^" in str(exc.value)


def test_parse_unbalanced():
    with pytest.raises(ParsingException):
        parse_one("SELECT (a FROM t")


def test_parse_create_model_kwargs():
    stmt = parse_one(
        """CREATE MODEL m WITH (
             model_class = 'sklearn.linear_model.LinearRegression',
             target_column = 'y', wrap_predict = True, n = 3, f = 1.5,
             tags = ARRAY ['a', 'b'], nested = (x = 1)
           ) AS (SELECT 1 AS y)""")
    assert isinstance(stmt, A.CreateModel)
    assert stmt.kwargs["model_class"] == "sklearn.linear_model.LinearRegression"
    assert stmt.kwargs["wrap_predict"] is True
    assert stmt.kwargs["n"] == 3
    assert stmt.kwargs["f"] == 1.5
    assert stmt.kwargs["tags"] == ["a", "b"]
    assert stmt.kwargs["nested"] == {"x": 1}


def test_parse_operator_precedence():
    stmt = parse_one("SELECT 1 + 2 * 3")
    expr = stmt.query.projections[0][0]
    assert expr.op == "+"
    assert expr.args[1].op == "*"


def test_parse_multiple():
    stmts = parse_sql("SELECT 1; SELECT 2;")
    assert len(stmts) == 2


def test_parse_case_sensitivity():
    stmt = parse_one("select A, b from T")
    q = stmt.query
    assert q.projections[0][0].parts == ["A"]
    assert q.from_.parts == ["T"]


def test_parse_window():
    stmt = parse_one(
        "SELECT SUM(x) OVER (PARTITION BY g ORDER BY d DESC ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) FROM t")
    call = stmt.query.projections[0][0]
    assert call.over is not None
    assert len(call.over.partition_by) == 1
    assert call.over.order_by[0].ascending is False
    assert call.over.frame == ("ROWS", ("PRECEDING", 2), ("CURRENT", None))
