"""Differential tests: native C++ parser vs the pure-Python parser.

Every statement in the corpus must produce structurally identical ASTs from
both front-ends (dataclass equality), including positions — the strongest
oracle available for the native planner (mirrors the reference's strategy of
validating its native planner through the Python integration suite).
"""
import pytest

from dask_sql_tpu import native
from dask_sql_tpu.sql import native_bridge
from dask_sql_tpu.sql.parser import Parser
from dask_sql_tpu.utils import ParsingException

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native parser library unavailable")

CORPUS = [
    # projections / expressions
    "SELECT 1",
    "SELECT 1 + 1 AS two, -3.5e2, .5, 'it''s', NULL, TRUE, FALSE",
    "SELECT a, b AS c, t.*, * FROM t",
    "SELECT DISTINCT a FROM t",
    "SELECT a + b * c - d / e % f, a || b || 'x' FROM t",
    "SELECT (a + b) * (c - d) FROM t",
    "SELECT CASE WHEN a > 1 THEN 'x' WHEN a > 0 THEN 'y' ELSE 'z' END FROM t",
    "SELECT CASE a WHEN 1 THEN 'one' ELSE 'many' END FROM t",
    "SELECT CAST(a AS DOUBLE), CAST(b AS DECIMAL(10, 2)), a :: VARCHAR FROM t",
    "SELECT CAST(a AS DOUBLE PRECISION) FROM t",
    "SELECT a IS NULL, b IS NOT NULL, c IS TRUE, d IS NOT FALSE, e IS UNKNOWN FROM t",
    "SELECT a IS DISTINCT FROM b, a IS NOT DISTINCT FROM b FROM t",
    "SELECT a BETWEEN 1 AND 10, b NOT BETWEEN SYMMETRIC 2 AND 0 FROM t",
    "SELECT a IN (1, 2, 3), b NOT IN ('x', 'y') FROM t",
    "SELECT a LIKE 'x%', b NOT LIKE '_y' ESCAPE '\\', c ILIKE '%Z%' FROM t",
    "SELECT a SIMILAR TO 'x|y', b NOT SIMILAR TO '[0-9]*' FROM t",
    "SELECT NOT a OR b AND NOT c FROM t",
    "SELECT a = 1, b <> 2, c != 3, d < 4, e <= 5, f > 6, g >= 7 FROM t",
    "SELECT -a, +b, -(-c) FROM t",
    "SELECT SUM(x), COUNT(*), COUNT(DISTINCT y), AVG(ALL z) FROM t",
    "SELECT SUM(x) FILTER (WHERE y > 0) FROM t",
    'SELECT "Quoted Col", `backtick`, "with""quote" FROM "My Table"',
    "SELECT f(a, b, c), g(), my_udf(x + 1) FROM t",
    # string/date builtins with special syntax
    "SELECT SUBSTRING('hello' FROM 2 FOR 3), SUBSTRING(s, 1, 2), SUBSTRING(s, 5) FROM t",
    "SELECT TRIM(s), TRIM(BOTH 'x' FROM s), TRIM(LEADING FROM s), TRIM(TRAILING 'y' FROM s) FROM t",
    "SELECT POSITION('a' IN s), OVERLAY(s PLACING 'xx' FROM 2 FOR 3), OVERLAY(s PLACING 'y' FROM 1) FROM t",
    "SELECT EXTRACT(YEAR FROM d), EXTRACT(DOW FROM d) FROM t",
    "SELECT CEIL(x), CEILING(y), FLOOR(z), CEIL(d TO MONTH), FLOOR(d TO DAY) FROM t",
    "SELECT CURRENT_DATE, CURRENT_TIMESTAMP, LOCALTIMESTAMP FROM t",
    "SELECT DATE '2020-01-01', TIMESTAMP '2020-01-01 10:00:00', TIME '10:11:12'",
    "SELECT INTERVAL '3' DAY, INTERVAL 5 HOURS, INTERVAL - 2 MINUTE, INTERVAL '1-2' YEAR TO MONTH",
    "SELECT ROW(1, 'x'), (a, b) = (1, 2) FROM t",
    # FROM / joins
    "SELECT * FROM a, b, c",
    "SELECT * FROM a JOIN b ON a.x = b.y",
    "SELECT * FROM a INNER JOIN b ON a.x = b.y LEFT JOIN c ON b.z = c.w",
    "SELECT * FROM a LEFT OUTER JOIN b ON a.x = b.y",
    "SELECT * FROM a RIGHT JOIN b USING (x, y)",
    "SELECT * FROM a FULL OUTER JOIN b ON a.x = b.y OR a.z < b.w",
    "SELECT * FROM a CROSS JOIN b",
    "SELECT * FROM a NATURAL JOIN b",
    "SELECT * FROM (SELECT x FROM t) AS sub (col1)",
    "SELECT * FROM (SELECT x FROM t) sub",
    "SELECT * FROM schema1.table1 AS t1 (a, b)",
    "SELECT * FROM t TABLESAMPLE SYSTEM (20)",
    "SELECT * FROM t TABLESAMPLE BERNOULLI (50.5) REPEATABLE (42)",
    "SELECT * FROM (a JOIN b ON a.x = b.y) JOIN c ON b.z = c.w",
    # grouping / having / sorting / limits
    "SELECT a, SUM(b) FROM t GROUP BY a HAVING SUM(b) > 10",
    "SELECT a, b, COUNT(*) FROM t GROUP BY (a, b)",
    "SELECT a FROM t GROUP BY ()",
    "SELECT a FROM t ORDER BY a DESC, b ASC NULLS FIRST, c NULLS LAST LIMIT 10 OFFSET 5",
    "SELECT a FROM t ORDER BY 1 FETCH FIRST 3 ROWS ONLY",
    "SELECT a FROM t LIMIT 2 + 3",
    # set ops / CTEs / values
    "SELECT a FROM t UNION SELECT b FROM u",
    "SELECT a FROM t UNION ALL SELECT b FROM u INTERSECT SELECT c FROM v",
    "SELECT a FROM t EXCEPT DISTINCT SELECT b FROM u ORDER BY a LIMIT 1",
    "SELECT a FROM t MINUS SELECT b FROM u",
    "WITH x AS (SELECT 1 AS a), y AS (SELECT a + 1 AS b FROM x) SELECT * FROM y",
    "WITH x AS (SELECT 1 AS a) SELECT a FROM x UNION SELECT a FROM x",
    "VALUES (1, 'a'), (2, 'b')",
    "SELECT * FROM (VALUES (1, 2), (3, 4)) AS v (x, y)",
    "(SELECT a FROM t) UNION (SELECT b FROM u)",
    # subqueries
    "SELECT (SELECT MAX(x) FROM t) AS m",
    "SELECT a FROM t WHERE a IN (SELECT b FROM u)",
    "SELECT a FROM t WHERE a NOT IN (SELECT b FROM u WHERE c > 0)",
    "SELECT a FROM t WHERE EXISTS (SELECT 1 FROM u WHERE u.x = t.a)",
    "SELECT a FROM t WHERE a > ANY (SELECT b FROM u)",
    "SELECT a FROM t WHERE a <= ALL (SELECT b FROM u)",
    "SELECT a FROM t WHERE a = SOME (SELECT b FROM u)",
    # window functions
    "SELECT ROW_NUMBER() OVER (PARTITION BY a ORDER BY b DESC) FROM t",
    "SELECT SUM(x) OVER (PARTITION BY a, b ORDER BY c ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) FROM t",
    "SELECT SUM(x) OVER (ROWS BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING) FROM t",
    "SELECT COUNT(*) OVER (ORDER BY a RANGE UNBOUNDED PRECEDING) FROM t",
    "SELECT FIRST_VALUE(x) OVER (PARTITION BY g ORDER BY o ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) FROM t",
    # custom statements (reference grammar: create/model/show ftl)
    "CREATE TABLE t2 WITH (location = 'data.csv', format = 'csv', persist = True)",
    "CREATE OR REPLACE TABLE t2 WITH (gpu = False, x = 3, y = -1.5, z = NULL)",
    "CREATE TABLE IF NOT EXISTS t2 AS (SELECT * FROM t)",
    "CREATE VIEW v AS (SELECT a FROM t WHERE a > 0)",
    "CREATE OR REPLACE VIEW v AS SELECT 1",
    "CREATE SCHEMA myschema",
    "CREATE SCHEMA IF NOT EXISTS other",
    "DROP SCHEMA IF EXISTS other",
    "DROP TABLE IF EXISTS t2",
    "DROP MODEL IF EXISTS m",
    "USE SCHEMA myschema",
    "SHOW SCHEMAS",
    "SHOW SCHEMAS LIKE 'foo'",
    "SHOW TABLES",
    "SHOW TABLES FROM myschema",
    "SHOW COLUMNS FROM t",
    "SHOW COLUMNS FROM myschema.t",
    "SHOW MODELS",
    "DESCRIBE MODEL m",
    "DESCRIBE t",
    "ANALYZE TABLE t COMPUTE STATISTICS FOR ALL COLUMNS",
    "ANALYZE TABLE t COMPUTE STATISTICS FOR COLUMNS a, b",
    "CREATE MODEL m WITH (model_class = 'sklearn.linear_model.LinearRegression', "
    "target_column = 'y', wrap_predict = True, n = 3, f = 1.5, "
    "tags = ARRAY ['a', 'b'], nested = (x = 1), m2 = MAP ['k', 'v']) AS (SELECT 1 AS y)",
    "CREATE EXPERIMENT e WITH (automl_class = 'x.Y') AS (SELECT a, y FROM t)",
    "EXPORT MODEL m WITH (format = 'pickle', location = '/tmp/m.pkl')",
    "SELECT * FROM PREDICT(MODEL m, SELECT a, b FROM t)",
    "SELECT * FROM PREDICT(MODEL s.m, SELECT a FROM t) AS p",
    "EXPLAIN SELECT a FROM t WHERE a > 0",
    # multiple statements
    "SELECT 1; SELECT 2;",
    "CREATE SCHEMA s1; USE SCHEMA s1; SELECT 1",
    # outer ORDER BY/LIMIT over raw bodies (must wrap, not merge/drop)
    "VALUES (1), (2), (3) LIMIT 2",
    "VALUES (1), (2), (3) ORDER BY 1 DESC LIMIT 1 OFFSET 1",
    "(SELECT a FROM t) ORDER BY a",
    "(SELECT a FROM t ORDER BY a LIMIT 5) LIMIT 2",
    "(SELECT a FROM t UNION SELECT b FROM s ORDER BY 1) LIMIT 2",
    "WITH c AS (SELECT a FROM t) SELECT a FROM c UNION ALL SELECT 9"
    " ORDER BY 1 LIMIT 3 OFFSET 1",
    "SELECT a FROM t UNION SELECT b FROM s ORDER BY 1 LIMIT 3",
]


@pytest.mark.parametrize("sql", CORPUS, ids=range(len(CORPUS)))
def test_native_matches_python(sql):
    envelope = native.parse_to_json(sql)
    assert envelope is not None
    native_ast = native_bridge.json_to_statements(envelope, sql)
    python_ast = Parser(sql).parse_statements()
    assert native_ast == python_ast


def test_original_name_preserved():
    sql = "SELECT MyUdf(x) FROM t"
    native_ast = native_bridge.json_to_statements(native.parse_to_json(sql), sql)
    python_ast = Parser(sql).parse_statements()
    n_call = native_ast[0].query.projections[0][0]
    p_call = python_ast[0].query.projections[0][0]
    assert n_call.original_name == p_call.original_name == "MyUdf"


ERROR_CORPUS = [
    "SELECT FROM FROM t",
    "SELECT (a FROM t",
    "SELECT * FROM",
    "CREATE TABLE",
    "SELECT a FROM t WHERE",
    "SELECT 'unterminated",
    "SELECT a FROM t GROUP",
    "FROB THE KNOB",
    "SELECT a b c, FROM t",
    # truncated statements must error cleanly, not read past the END token
    "SHOW SCHEMAS LIKE",
    "SELECT CAST(a AS DECIMAL(",
    "SELECT a FROM t ORDER BY",
    "SELECT INTERVAL",
]


def test_interval_nonfinite_value():
    """Overflowing interval strings survive the JSON round trip (inf/nan)."""
    for sql in ("SELECT INTERVAL '1e400' DAY", "SELECT INTERVAL '-1e400' DAY"):
        n = native_bridge.json_to_statements(native.parse_to_json(sql), sql)
        p = Parser(sql).parse_statements()
        assert n == p


@pytest.mark.parametrize("sql", ERROR_CORPUS, ids=range(len(ERROR_CORPUS)))
def test_native_errors_match_python_positions(sql):
    """Both parsers must reject, reporting the same error position."""
    with pytest.raises(ParsingException) as native_exc:
        stmts = native_bridge.json_to_statements(native.parse_to_json(sql), sql)
        assert stmts is None, f"native parser accepted: {sql}"
    with pytest.raises(ParsingException):
        Parser(sql).parse_statements()
    assert "^" in str(native_exc.value) or "Unterminated" in str(native_exc.value)
