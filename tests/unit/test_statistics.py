"""Unit tests for runtime/statistics.py: ingest collection, NDV
estimation, dense-domain detection, the hash/sort crossover table,
selectivity/cardinality rules, and the scheduler's stats estimate slot.

The module name contains "statistic", so conftest's _adaptive_off pin
leaves DSQL_ADAPTIVE at its production default (on) here.
"""
import numpy as np
import pandas as pd
import pytest

from dask_sql_tpu import Context
from dask_sql_tpu.runtime import statistics as stats
from dask_sql_tpu.runtime import telemetry as _tel


def _ctx(**frames):
    c = Context()
    for name, frame in frames.items():
        c.create_table(name, frame)
    return c


# ---------------------------------------------------------------------------
# collection
# ---------------------------------------------------------------------------

def test_collect_basic_int_column():
    c = _ctx(t=pd.DataFrame({"k": [3, 1, 2, 3, 1], "v": [1.0, 2, 3, 4, 5]}))
    ts = c.schema["root"].tables["t"].stats
    assert ts is not None and ts.rows == 5
    k = ts.col("k")
    assert k.ndv == 3 and k.min == 1 and k.max == 3
    assert k.is_int and k.dense and k.domain == 3
    assert k.null_frac == 0.0


def test_collect_null_fraction():
    c = _ctx(t=pd.DataFrame({"k": pd.array([1, None, 3, None], "Int64")}))
    k = c.schema["root"].tables["t"].stats.col("k")
    assert k.null_frac == pytest.approx(0.5)
    # min/max are over VALID rows only
    assert k.min == 1 and k.max == 3


def test_collect_string_ndv_from_dictionary():
    c = _ctx(t=pd.DataFrame({"s": ["a", "b", "a", "c", "b"]}))
    s = c.schema["root"].tables["t"].stats.col("s")
    assert s.ndv == 3 and not s.is_int and not s.dense


def test_collect_wide_domain_not_dense():
    c = _ctx(t=pd.DataFrame({"k": np.arange(0, 10**7, 1000)}))
    k = c.schema["root"].tables["t"].stats.col("k")
    assert k.is_int and not k.dense
    assert k.domain > stats.dense_domain_cap()


def test_dense_domain_cap_env(monkeypatch):
    monkeypatch.setenv("DSQL_DENSE_DOMAIN_CAP", "8")
    assert stats.dense_domain_cap() == 8
    c = _ctx(t=pd.DataFrame({"k": [0, 100]}))
    assert not c.schema["root"].tables["t"].stats.col("k").dense


def test_sampled_ndv_exact_when_small():
    assert stats._sampled_ndv(np.array([1, 2, 2, 3])) == 3


def test_sampled_ndv_extrapolates_keylike():
    # a key-like column (all distinct) extrapolates to ~n
    n = 200_000
    est = stats._sampled_ndv(np.arange(n, dtype=np.int64))
    assert est >= 0.9 * n


def test_sampled_ndv_lower_bound_when_fat():
    # few distinct values: reported count stays near the true NDV, never
    # extrapolated past it
    n = 200_000
    est = stats._sampled_ndv(np.arange(n, dtype=np.int64) % 7)
    assert est <= 7


def test_collection_counter_and_never_raises():
    before = _tel.REGISTRY.counters().get("stats_tables_collected", 0)
    _ctx(t=pd.DataFrame({"a": [1]}))
    after = _tel.REGISTRY.counters().get("stats_tables_collected", 0)
    assert after == before + 1
    assert stats.collect_table_stats(object()) is None  # junk, no raise


# ---------------------------------------------------------------------------
# crossover table
# ---------------------------------------------------------------------------

def test_crossover_dense_small_domain():
    assert stats.choose_groupby_variant(10**6, 100, dense_ok=True) == "dense"


def test_crossover_sorted_fat_groups():
    assert stats.choose_groupby_variant(10**6, 1000,
                                        dense_ok=False) == "sorted"


def test_crossover_hash_high_ndv():
    assert stats.choose_groupby_variant(10**6, 500_000,
                                        dense_ok=False) == "hash"


def test_crossover_hash_when_groups_thin():
    # ndv below SORT_NDV_CAP but groups too thin (rows/ndv < fraction)
    assert stats.choose_groupby_variant(1000, 900, dense_ok=False) == "hash"


def test_crossover_unknown_stats_status_quo():
    assert stats.choose_groupby_variant(None, None, dense_ok=False) == "hash"


def test_crossover_forced_override(monkeypatch):
    monkeypatch.setenv("DSQL_FORCE_GROUPBY", "sorted")
    assert stats.forced_groupby() == "sorted"
    monkeypatch.setenv("DSQL_FORCE_GROUPBY", "bogus")
    assert stats.forced_groupby() is None


def test_adaptive_kill_switch(monkeypatch):
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    assert not stats.adaptive_enabled()
    monkeypatch.setenv("DSQL_ADAPTIVE", "1")
    assert stats.adaptive_enabled()


# ---------------------------------------------------------------------------
# selectivity + cardinality
# ---------------------------------------------------------------------------

def _plan(c, sql):
    from dask_sql_tpu.sql.parser import parse_sql
    stmt = parse_sql(sql)[0]
    return c._get_plan(getattr(stmt, "query", stmt), sql)


def test_estimate_rows_scan_and_filter():
    n = 1000
    c = _ctx(t=pd.DataFrame({"k": np.arange(n), "v": np.random.rand(n)}))
    scan = _plan(c, "SELECT * FROM t")
    assert stats.estimate_rows(scan, c) == pytest.approx(n, rel=0.01)
    # range predicate over a uniform domain: min/max interpolation
    filt = _plan(c, "SELECT * FROM t WHERE k < 100")
    est = stats.estimate_rows(filt, c)
    assert est is not None and 20 <= est <= 400


def test_estimate_rows_equality_uses_ndv():
    c = _ctx(t=pd.DataFrame({"k": np.arange(1000) % 10}))
    filt = _plan(c, "SELECT * FROM t WHERE k = 3")
    est = stats.estimate_rows(filt, c)
    assert est == pytest.approx(100, rel=0.5)


def test_estimate_rows_aggregate_ndv_product():
    c = _ctx(t=pd.DataFrame({"k": np.arange(5000) % 25,
                             "v": np.random.rand(5000)}))
    agg = _plan(c, "SELECT k, SUM(v) FROM t GROUP BY k")
    est = stats.estimate_rows(agg, c)
    assert est == pytest.approx(25, rel=0.3)


def test_estimate_join_rows_equi_selectivity():
    nl, d = 10_000, 100
    c = _ctx(l=pd.DataFrame({"k": np.arange(nl) % d}),
             r=pd.DataFrame({"k": np.arange(d)}))
    j = _plan(c, "SELECT * FROM l, r WHERE l.k = r.k")
    est = stats.estimate_rows(j, c)
    # |l| * |r| / max-ndv = 10000 * 100 / 100 = 10000
    assert est == pytest.approx(nl, rel=0.5)


def test_estimate_plan_bytes_stats_and_scheduler_source(monkeypatch):
    from dask_sql_tpu.runtime import scheduler as sched
    c = _ctx(t=pd.DataFrame({"k": np.arange(1000) % 10,
                             "v": np.random.rand(1000)}))
    plan = _plan(c, "SELECT k, SUM(v) FROM t GROUP BY k")
    est = stats.estimate_plan_bytes_stats(plan, c)
    assert est is not None and est > 0
    nbytes, source = sched.estimate_working_set(plan, c)
    assert source == "stats" and nbytes >= est
    # kill switch restores the heuristic source
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    _, source = sched.estimate_working_set(plan, c)
    assert source == "heuristic"


# ---------------------------------------------------------------------------
# cap hints + stats join reorder
# ---------------------------------------------------------------------------

def test_compiled_cap_hints_single_aggregate():
    c = _ctx(t=pd.DataFrame({"k": np.arange(4000) % 40,
                             "v": np.random.rand(4000)}))
    plan = _plan(c, "SELECT k, SUM(v) FROM t GROUP BY k")
    hints = stats.compiled_cap_hints(plan, c)
    assert set(hints) == {"agg0"}
    cap = hints["agg0"]
    assert cap >= 40 and cap & (cap - 1) == 0  # power of two, fits groups


def test_compiled_cap_hints_silent_when_off(monkeypatch):
    c = _ctx(t=pd.DataFrame({"k": [1, 2], "v": [1.0, 2.0]}))
    plan = _plan(c, "SELECT k, SUM(v) FROM t GROUP BY k")
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    assert stats.compiled_cap_hints(plan, c) == {}


def test_reorder_joins_stats_smaller_build_first():
    np.random.seed(0)
    big = pd.DataFrame({"k": np.random.randint(0, 50, 20_000)})
    dim = pd.DataFrame({"k": np.arange(50), "d": np.arange(50) % 5})
    tiny = pd.DataFrame({"d": np.arange(5)})
    c = _ctx(big=big, dim=dim, tiny=tiny)
    text = c.sql(
        "EXPLAIN SELECT COUNT(*) FROM big, dim, tiny "
        "WHERE big.k = dim.k AND dim.d = tiny.d"
    ).to_pandas()["PLAN"].str.cat(sep="\n")
    # the 20k-row fact table must not be the build start of the chain:
    # stats ordering joins dim x tiny first, then attaches big
    assert text.index("big") > text.index("dim")


def test_reorder_joins_stats_disabled_keeps_plan(monkeypatch):
    monkeypatch.setenv("DSQL_ADAPTIVE", "0")
    from dask_sql_tpu.plan.optimizer import reorder_joins_stats
    c = _ctx(t=pd.DataFrame({"k": [1]}))
    plan = _plan(c, "SELECT * FROM t")
    assert reorder_joins_stats(plan, c) is plan


# ---------------------------------------------------------------------------
# explain surface + system rows
# ---------------------------------------------------------------------------

def test_explain_lines_groupby():
    c = _ctx(t=pd.DataFrame({"k": np.arange(2000) % 20,
                             "v": np.random.rand(2000)}))
    plan = _plan(c, "SELECT k, SUM(v) FROM t GROUP BY k")
    lines = stats.explain_lines(plan, c)
    assert any(ln.startswith("-- operator: groupby=") for ln in lines)
    assert any("ndv=20" in ln and "rows=2000" in ln for ln in lines)


def test_system_rows_shape():
    c = _ctx(t=pd.DataFrame({"k": [1, 2, 2], "s": ["x", "y", "x"]}))
    rows = stats.system_rows(c)
    by_col = {(r["table"], r["column"]): r for r in rows}
    assert by_col[("t", "k")]["ndv"] == 2
    assert by_col[("t", "s")]["ndv"] == 2
    assert by_col[("t", "k")]["rows"] == 3


def test_format_choice_stable():
    line = stats.format_choice("groupby", "dense", {"rows": 7, "ndv": 3})
    assert line == "groupby=dense ndv=3 rows=7"
