#!/usr/bin/env python
"""Out-of-core smoke gate: TPC-H-shaped queries over chunked tables that
exceed a deliberately tiny device budget must complete CORRECTLY through
the spill path (runtime/spill.py + physical/morsel.py), with bounded
device occupancy — and DSQL_SPILL_MB=0 must restore pre-spill behavior.

Four checks (run by scripts/ci_local.sh as ``python scripts/ooc_smoke.py``):

  1. Q1/Q6 shapes (scan -> filter -> wide aggregate) over ONE chunked
     table stream per-batch and match the pandas oracle — including a
     short final batch and NULLs in an aggregated column;
  2. a Q3 shape (two CHUNKED tables joined on a key, then GROUP BY) runs
     the grace-hash partitioned join: spill_partitions advances, the
     result matches pandas (NULL join keys dropped per INNER semantics),
     and every spill run is freed afterwards;
  3. the spill store's device tier stays bounded: peak_device_bytes never
     exceeds the configured device cap;
  4. DSQL_SPILL_MB=0 (spilling OFF) keeps single-chunked streaming
     byte-identical and turns the two-chunked join back into the typed
     StreamingUnsupported error the engine raised before the subsystem.

Exit 0 on success.
"""
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# a deliberately small ledger budget: the chunked tables below would not
# fit resident, so completing correctly PROVES the out-of-core path
os.environ.setdefault("DSQL_DEVICE_BUDGET_MB", "64")
os.environ.setdefault("DSQL_SPILL_MB", "64")
os.environ.setdefault("DSQL_SPILL_DEVICE_MB", "8")
os.environ.setdefault("DSQL_SPILL_DIR",
                      tempfile.mkdtemp(prefix="dsql_ooc_smoke_"))

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

N_LINE = 120_000
N_ORD = 30_000
BATCH_ROWS = 16_384


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64").round(6)
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def _check(name, got, oracle, failures):
    try:
        pd.testing.assert_frame_equal(_norm(got), _norm(oracle),
                                      check_dtype=False, rtol=1e-6,
                                      atol=1e-9)
        print(f"  {name}: correct ({len(got)} rows)")
    except AssertionError as e:
        failures.append(f"{name} wrong result: {str(e)[:300]}")


def _make_data(seed=0):
    rng = np.random.default_rng(seed)
    # lineitem-shaped: status strings, a NULL-bearing measure, and a row
    # count that leaves a SHORT final batch (120000 % 16384 != 0)
    qty = rng.integers(1, 50, N_LINE).astype("float64")
    qty[rng.random(N_LINE) < 0.02] = np.nan
    line = pd.DataFrame({
        "okey": rng.integers(0, N_ORD, N_LINE),
        "qty": qty,
        "price": np.round(rng.random(N_LINE) * 1000, 2),
        "disc": np.round(rng.random(N_LINE) * 0.1, 2),
        "status": rng.choice(["A", "B", "C"], N_LINE),
    })
    okey = np.arange(N_ORD, dtype="float64")
    okey[rng.random(N_ORD) < 0.01] = np.nan  # NULL join keys
    orders = pd.DataFrame({
        "okey": okey,
        "seg": rng.choice(["AUTO", "HOME", "SHIP"], N_ORD),
        "total": np.round(rng.random(N_ORD) * 5000, 2),
    })
    return line, orders


def main() -> int:
    from dask_sql_tpu import Context
    from dask_sql_tpu.runtime import resilience as res
    from dask_sql_tpu.runtime import spill as spill_mod
    from dask_sql_tpu.runtime import telemetry as tel

    line, orders = _make_data()
    failures = []

    ctx = Context()
    ctx.create_table("line", line, chunked=True, batch_rows=BATCH_ROWS)
    ctx.create_table("orders", orders, chunked=True, batch_rows=BATCH_ROWS)

    q1 = ("SELECT status, SUM(qty) AS sq, SUM(price * (1.0 - disc)) AS sp, "
          "COUNT(*) AS n FROM line GROUP BY status")
    o1 = line.groupby("status", as_index=False).agg(
        sq=("qty", "sum"),
        sp=("price", lambda s: float("nan")),  # recomputed below
        n=("qty", "size"))
    o1["sp"] = line.assign(x=line.price * (1.0 - line.disc)).groupby(
        "status")["x"].sum().reindex(o1.status).to_numpy()
    q6 = ("SELECT SUM(price * disc) AS rev FROM line "
          "WHERE disc > 0.02 AND qty < 25.0")
    f6 = line[(line.disc > 0.02) & (line.qty < 25.0)]
    o6 = pd.DataFrame({"rev": [(f6.price * f6.disc).sum()]})
    q3 = ("SELECT orders.seg AS seg, SUM(line.price) AS rev, COUNT(*) AS n "
          "FROM line JOIN orders ON line.okey = orders.okey "
          "GROUP BY orders.seg")
    j = line.merge(orders, on="okey")  # pandas merge drops NaN keys: INNER
    o3 = j.groupby("seg", as_index=False).agg(rev=("price", "sum"),
                                              n=("price", "size"))

    print("[1] single-chunked streaming (Q1/Q6 shapes)")
    _check("Q1-shape", ctx.sql(q1, return_futures=False), o1, failures)
    _check("Q6-shape", ctx.sql(q6, return_futures=False), o6, failures)

    print("[2] two-chunked grace-hash join (Q3 shape)")
    c0 = tel.REGISTRY.counters()
    _check("Q3-shape", ctx.sql(q3, return_futures=False), o3, failures)
    c1 = tel.REGISTRY.counters()
    parts = c1.get("spill_partitions", 0) - c0.get("spill_partitions", 0)
    joins = c1.get("morsel_joins", 0) - c0.get("morsel_joins", 0)
    if parts <= 0 or joins <= 0:
        failures.append(
            f"grace path did not run: spill_partitions delta {parts}, "
            f"morsel_joins delta {joins}")
    else:
        print(f"  grace join ran: {parts} spill partitions, "
              f"{joins} morsel join(s)")
    stats = spill_mod.get_store().stats()
    if stats["runs"]:
        failures.append(f"spill store leaked {stats['runs']} run(s)")

    print("[3] device occupancy bounded")
    peak = stats["peak_device_bytes"]
    cap = spill_mod.device_cap_bytes()
    if peak > cap:
        failures.append(f"spill device tier exceeded its cap: "
                        f"peak {peak} > cap {cap}")
    else:
        print(f"  peak spill device bytes {peak} <= cap {cap}")

    print("[4] DSQL_SPILL_MB=0 restores pre-spill behavior")
    os.environ["DSQL_SPILL_MB"] = "0"
    spill_mod.reset_store()
    ctx0 = Context()
    ctx0.create_table("line", line, chunked=True, batch_rows=BATCH_ROWS)
    ctx0.create_table("orders", orders, chunked=True, batch_rows=BATCH_ROWS)
    _check("Q1-shape (spill off)", ctx0.sql(q1, return_futures=False), o1,
           failures)
    c2 = tel.REGISTRY.counters()
    try:
        ctx0.sql(q3, return_futures=False)
        failures.append("two-chunked join succeeded with spilling OFF — "
                        "DSQL_SPILL_MB=0 did not restore the baseline")
    except res.ResilienceError as e:
        print(f"  two-chunked join raised typed "
              f"{type(e).__name__} (expected)")
    c3 = tel.REGISTRY.counters()
    if c3.get("spill_partitions", 0) != c2.get("spill_partitions", 0):
        failures.append("spill counters advanced with spilling OFF")

    if failures:
        print("OOC SMOKE FAILED:")
        for f in failures:
            print("  - " + f)
        return 1
    print("ooc smoke OK: chunked Q1/Q6/Q3 shapes correct, grace join "
          "spilled and freed, device occupancy bounded, kill switch clean")
    return 0


if __name__ == "__main__":
    rc = main()
    # skip interpreter teardown (same discipline as bench.py's stage
    # children): the XLA CPU client occasionally aborts in its destructor
    # after heavy device-buffer churn, long after every check has passed
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(rc)
