#!/usr/bin/env python
"""Fleet-armor smoke gate: result paging, tenant quotas, and kill switches.

Run by scripts/ci_local.sh (mirroring scripts/events_smoke.py):

    python scripts/fleet_smoke.py

Against ONE live server (env knobs are read per call, so phases flip
them without a restart) the gate proves

  1. a ~1M-row result pages through the spool behind a REAL ``nextUri``
     chain: every row arrives exactly once, the PEAK single-response
     payload stays under 10% of the whole, and the spill store is empty
     once the client drains the chain;
  2. a noisy tenant hammering a 2-slot server is throttled — 429 with an
     honest ``Retry-After`` it can actually sleep on — while a quiet
     tenant inside its own quota loses ZERO queries;
  3. a client that disconnects mid-pagination leaks nothing: within
     ``DSQL_RESULT_TTL_S`` the reaper frees its remaining pages AND its
     ``future_list``/seat entries, so ``/v1/engine`` shows no occupancy
     and the scheduler ends idle;
  4. both kill switches restore the pre-paging wire behavior:
     ``DSQL_RESULT_PAGE_ROWS=0`` serves the classic single-shot payload
     (same key set, whole result inline) and ``DSQL_TENANCY=0`` admits
     the noisy tenant unthrottled with no ``tenants`` engine section.

Exit 0 on success.
"""
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DSQL_TIERED", "0")
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "2"
os.environ.setdefault("DSQL_QUEUE_DEPTH", "64")
os.environ.setdefault("DSQL_QUEUE_TIMEOUT_MS", "120000")
os.environ.setdefault("DSQL_SPILL_DIR",
                      tempfile.mkdtemp(prefix="dsql_fleet_spill_"))
os.environ["DSQL_RESULT_PAGE_ROWS"] = "50000"
os.environ["DSQL_RESULT_TTL_S"] = "600"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import scheduler as sched  # noqa: E402
from dask_sql_tpu.runtime import spill as spill_mod  # noqa: E402
from dask_sql_tpu.server.app import run_server  # noqa: E402

BIG_ROWS = 1_000_000
PAGE_ROWS = 50_000
CLASSIC_KEYS = ["columns", "data", "id", "infoUri", "stats"]


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _post(base, sql, tenant=None):
    headers = {"X-DSQL-Tenant": tenant} if tenant else {}
    req = urllib.request.Request(f"{base}/v1/statement", data=sql.encode(),
                                 method="POST", headers=headers)
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=120) as r:
        raw = r.read()
        return json.loads(raw), len(raw)


def _poll(base, payload, timeout=120):
    """Follow /v1/status until the query finishes (a payload carrying
    data, or a nextUri that points at /v1/result)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        uri = payload.get("nextUri")
        if uri is None or "/v1/result/" in uri or "data" in payload:
            return payload
        time.sleep(0.05)
        payload, _ = _get(uri)
    raise AssertionError("query did not finish in time")


def _drain(base, sql, tenant=None):
    """Submit, poll, and walk the full page chain; returns
    (rows, [response_payload_bytes])."""
    payload = _poll(base, _post(base, sql, tenant=tenant))
    rows, sizes = [], []
    while True:
        data = payload.get("data")
        if data:
            rows.extend(data)
        sizes.append(len(json.dumps(payload).encode()))
        uri = payload.get("nextUri")
        if uri is None:
            return rows, sizes
        payload, _ = _get(uri)


def main() -> int:  # noqa: C901 - one linear smoke script
    ctx = Context()
    ctx.create_table("big", pd.DataFrame(
        {"a": np.arange(BIG_ROWS, dtype=np.int64)}))
    ctx.create_table("small", pd.DataFrame(
        {"a": np.arange(500, dtype=np.int64)}))
    srv = run_server(context=ctx, host="127.0.0.1", port=0, blocking=False)
    base = f"http://127.0.0.1:{srv.server_port}"
    state = srv.app_state
    try:
        # -- 1. the ~1M-row result pages, every row exactly once -----------
        rows, sizes = _drain(base, "SELECT a FROM big")
        if len(rows) != BIG_ROWS:
            return fail(f"paged result lost rows: {len(rows)} != {BIG_ROWS}")
        got = np.fromiter((r[0] for r in rows), dtype=np.int64,
                          count=BIG_ROWS)
        if not np.array_equal(np.sort(got), np.arange(BIG_ROWS)):
            return fail("paged result corrupted rows")
        peak, total = max(sizes), sum(sizes)
        if peak >= total * 0.10:
            return fail(f"peak single response {peak}B is >= 10% of the "
                        f"{total}B whole — paging is not actually paging")
        if spill_mod.get_store().stats()["runs"] or state.spools:
            return fail("pages leaked after a fully-drained chain")
        print(f"ok paging: {BIG_ROWS} rows over {len(sizes)} responses, "
              f"peak {peak / total:.1%} of {total >> 20} MiB total")

        # -- 2. noisy tenant throttled, quiet tenant loses zero ------------
        os.environ["DSQL_TENANT_QPS"] = "3"
        noisy = {"ok": 0, "throttled": 0, "bad_hint": 0, "other": 0}
        stop = time.time() + 4.0

        def noisy_client():
            while time.time() < stop:
                try:
                    p = _poll(base, _post(base, "SELECT COUNT(*) AS n "
                                                "FROM small",
                                          tenant="noisy"))
                    noisy["ok"] += 1 if p.get("data") else 0
                except urllib.error.HTTPError as e:
                    if e.code == 429:
                        ra = int(e.headers.get("Retry-After", "0"))
                        if 1 <= ra <= 5:
                            noisy["throttled"] += 1
                            time.sleep(min(ra, 0.5))  # the hint is usable
                        else:
                            noisy["bad_hint"] += 1
                    else:
                        noisy["other"] += 1

        th = threading.Thread(target=noisy_client, daemon=True)
        th.start()
        quiet_ok = 0
        for _ in range(6):
            p = _poll(base, _post(base, "SELECT SUM(a) AS s FROM small",
                                  tenant="quiet"))
            if p.get("data") == [[499 * 500 // 2]]:
                quiet_ok += 1
            time.sleep(0.5)
        th.join(timeout=30)
        os.environ.pop("DSQL_TENANT_QPS")
        if th.is_alive():
            return fail("noisy client hung")
        if noisy["throttled"] == 0:
            return fail(f"noisy tenant was never throttled: {noisy}")
        if noisy["bad_hint"] or noisy["other"]:
            return fail(f"throttle without an honest Retry-After: {noisy}")
        if noisy["ok"] == 0:
            return fail("noisy tenant was starved outright — the quota "
                        "should pace, not ban")
        if quiet_ok != 6:
            return fail(f"quiet tenant lost {6 - quiet_ok} of 6 queries "
                        "to a NOISY NEIGHBOR's pressure")
        eng, _ = _get(f"{base}/v1/engine")
        if not eng.get("tenants", {}).get("enabled"):
            return fail("/v1/engine has no tenants section while tenancy "
                        "is on")
        from dask_sql_tpu.runtime import tenancy
        rows = {r["tenant"]: r for r in tenancy.tenant_rows()}
        if rows.get("noisy", {}).get("quota_rejects", 0) == 0:
            return fail("system.tenants does not account the noisy "
                        "tenant's rejects")
        if rows["noisy"]["submitted"] != (rows["noisy"]["admitted"]
                                          + rows["noisy"]["quota_rejects"]
                                          + rows["noisy"]["circuit_rejects"]):
            return fail("noisy tenant's admission counters do not "
                        f"reconcile: {rows['noisy']}")
        print(f"ok tenants: noisy {noisy['ok']} ok + {noisy['throttled']} "
              f"throttled (honest hints), quiet 6/6")

        # -- 3. disconnect-mid-page: the reaper closes every tab -----------
        payload = _poll(base, _post(base, "SELECT a FROM big",
                                    tenant="flaky"))
        uid = payload["id"]
        _get(payload["nextUri"])            # take page 1... then vanish
        if uid not in state.spools:
            return fail("mid-pagination spool missing before the TTL")
        os.environ["DSQL_RESULT_TTL_S"] = "1"
        deadline = time.time() + 15
        while time.time() < deadline and (
                state.spools or state.future_list or state.seats):
            time.sleep(0.1)
        os.environ["DSQL_RESULT_TTL_S"] = "600"
        if state.spools or state.future_list or state.seats:
            return fail("reaper did not GC the disconnected client within "
                        f"the TTL: spools={list(state.spools)} "
                        f"futures={list(state.future_list)} "
                        f"seats={list(state.seats)}")
        if spill_mod.get_store().stats()["runs"]:
            return fail("disconnected client leaked spooled pages")
        eng, _ = _get(f"{base}/v1/engine")
        if eng["serverQueries"]:
            return fail(f"/v1/engine still lists occupancy after the reap: "
                        f"{eng['serverQueries']}")
        mgr = sched.get_manager()
        if mgr.running_count() != 0 or mgr.queue_depth() != 0:
            return fail("scheduler seats leaked past the reap: "
                        f"running={mgr.running_count()} "
                        f"queued={mgr.queue_depth()}")
        print("ok reaper: abandoned pages + future + seat GC'd within the "
              "TTL, zero /v1/engine occupancy")

        # -- 4. kill switches restore the pre-PR wire behavior -------------
        os.environ["DSQL_RESULT_PAGE_ROWS"] = "0"
        payload = _poll(base, _post(base, "SELECT a FROM small"))
        if sorted(payload.keys()) != CLASSIC_KEYS:
            return fail("DSQL_RESULT_PAGE_ROWS=0 payload keys drifted: "
                        f"{sorted(payload.keys())} != {CLASSIC_KEYS}")
        if len(payload["data"]) != 500 or "nextUri" in payload:
            return fail("DSQL_RESULT_PAGE_ROWS=0 did not restore the "
                        "single-shot result")
        os.environ["DSQL_TENANCY"] = "0"
        os.environ["DSQL_TENANT_QPS"] = "1"   # would throttle if consulted
        for _ in range(8):
            payload = _poll(base, _post(base, "SELECT COUNT(*) AS n "
                                              "FROM small",
                                        tenant="noisy"))
            if payload.get("data") != [[500]]:
                return fail("DSQL_TENANCY=0 altered a query result")
            if sorted(payload.keys()) != CLASSIC_KEYS:
                return fail("DSQL_TENANCY=0 payload keys drifted: "
                            f"{sorted(payload.keys())}")
        eng, _ = _get(f"{base}/v1/engine")
        if "tenants" in eng:
            return fail("DSQL_TENANCY=0 still surfaces a tenants section")
        os.environ.pop("DSQL_TENANT_QPS")
        os.environ.pop("DSQL_TENANCY")
        os.environ["DSQL_RESULT_PAGE_ROWS"] = "50000"
        print("ok kill switches: PAGE_ROWS=0 single-shot payload restored, "
              "TENANCY=0 admits 8/8 unthrottled with no tenants surface")
    finally:
        srv.shutdown()

    print("fleet smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
