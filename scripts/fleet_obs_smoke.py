#!/usr/bin/env python
"""Fleet-plane smoke gate: replica registry, cross-replica aggregation,
and the shared-warmth proof.

Run by scripts/ci_local.sh (mirroring scripts/events_smoke.py):

    python scripts/fleet_obs_smoke.py

With two REAL server children on one shared ``DSQL_FLEET_DIR`` +
``DSQL_PROGRAM_STORE`` the gate proves

  1. both replicas register live heartbeats and ``GET /v1/fleet`` (asked
     of either replica) reconciles with each replica's own
     ``GET /v1/engine`` — pids match, fleet totals equal the sum of the
     per-replica counters;
  2. shared warmth: replica A compiles a query shape and persists the
     programs; replica B then serves the SAME shape with ZERO XLA
     compiles (``dsql_compiles_total == 0`` on B's /metrics,
     ``program_store_hits > 0``) and an identical answer;
  3. one trace ID stitches across replicas: the merged
     ``system.events`` stream carries ``fleet-smoke-trace`` events
     stamped with BOTH replica ids, in global timestamp order;
  4. every /metrics series carries the ``replica`` label while armed;
  5. unset ``DSQL_FLEET_DIR`` restores the baseline exactly: a child
     with no fleet env never imports ``runtime.fleet``, serves the
     generic 404 on ``/v1/fleet``, exposes label-free /metrics, and
     returns bit-identical query results.

Exit 0 on success.
"""
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_TMP = tempfile.mkdtemp(prefix="dsql_fleet_obs_")
_FLEET_DIR = os.path.join(_TMP, "fleet")
_STORE_DIR = os.path.join(_TMP, "store")
os.makedirs(_STORE_DIR, exist_ok=True)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

QUERY = "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY k ORDER BY k"

# each replica: identical table shape, a server, then park
_CHILD = """
import os, time
import numpy as np
from dask_sql_tpu import Context
c = Context()
n = 4096
c.create_table("t", {"k": (np.arange(n, dtype=np.int64) % 32),
                     "v": np.arange(n, dtype=np.float64)})
srv = c.run_server(host="127.0.0.1", port=0, blocking=False)
print(f"PORT {srv.server_port}", flush=True)
while True:
    time.sleep(0.5)
"""


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _spawn_replica(rid: str):
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "DSQL_FLEET_DIR": _FLEET_DIR,
        "DSQL_REPLICA_ID": rid,
        "DSQL_FLEET_BEAT_S": "0.2",
        "DSQL_PROGRAM_STORE": _STORE_DIR,
        "DSQL_RESULT_CACHE_MB": "0",
        "DSQL_MAX_CONCURRENT_QUERIES": "0",
        "DSQL_ADAPTIVE": "0",
        "DSQL_TIERED": "0",
    })
    proc = subprocess.Popen([sys.executable, "-c", _CHILD], env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE)
    line = proc.stdout.readline().decode().strip()
    if not line.startswith("PORT "):
        proc.kill()
        raise RuntimeError(f"replica {rid} failed to start: {line!r} "
                           f"{proc.stderr.read().decode()[-500:]}")
    return proc, f"http://127.0.0.1:{line.split()[1]}"


def _req(url, body=None, headers=None):
    req = urllib.request.Request(
        url, data=body.encode() if body is not None else None,
        headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read() or b"null"), dict(r.headers)


def _run_query(base, sql, trace):
    payload, _ = _req(f"{base}/v1/statement", sql,
                      headers={"X-DSQL-Trace": trace})
    while "nextUri" in payload:
        payload, _ = _req(payload["nextUri"])
    return payload


def _metric(base, name):
    """One counter value off /metrics, label-blind."""
    with urllib.request.urlopen(f"{base}/metrics", timeout=60) as r:
        for line in r.read().decode().splitlines():
            if line.startswith("#"):
                continue
            key = line.split("{")[0].split(" ")[0]
            if key == name:
                return float(line.rsplit(" ", 1)[1])
    return None


def main() -> int:
    os.environ["DSQL_FLEET_DIR"] = _FLEET_DIR   # parent reads, read-only
    from dask_sql_tpu.runtime import fleet

    proc_a = proc_b = None
    try:
        proc_a, base_a = _spawn_replica("r-a")
        proc_b, base_b = _spawn_replica("r-b")
        print(f"ok spawn: r-a at {base_a}, r-b at {base_b}")

        # -- 1. warmth: A compiles, B serves the same shape warm ----------
        res_a = _run_query(base_a, QUERY, "fleet-smoke-trace")
        compiles_a = _metric(base_a, "dsql_compiles_total")
        if not compiles_a:
            return fail(f"replica A reported no compiles: {compiles_a}")
        res_b = _run_query(base_b, QUERY, "fleet-smoke-trace")
        if res_b["data"] != res_a["data"]:
            return fail(f"replica answers differ: {res_b['data'][:2]} "
                        f"vs {res_a['data'][:2]}")
        compiles_b = _metric(base_b, "dsql_compiles_total")
        hits_b = _metric(base_b, "dsql_program_store_hits_total")
        if compiles_b != 0:
            return fail(f"replica B compiled ({compiles_b}) instead of "
                        "serving A's programs warm")
        if not hits_b:
            return fail(f"replica B shows no program-store hits: {hits_b}")
        print(f"ok warmth: A compiled {compiles_a:.0f}, B served warm "
              f"(compiles=0, store hits={hits_b:.0f})")

        # -- 2. /v1/fleet reconciles with per-replica /v1/engine ----------
        eng_a, _ = _req(f"{base_a}/v1/engine")
        eng_b, _ = _req(f"{base_b}/v1/engine")
        for eng, rid in ((eng_a, "r-a"), (eng_b, "r-b")):
            if eng.get("fleet", {}).get("replica") != rid:
                return fail(f"/v1/engine fleet stamp wrong: {eng.get('fleet')}")
        deadline = time.time() + 10
        while True:
            snap, _ = _req(f"{base_a}/v1/fleet")
            rows = {r["replica"]: r for r in snap["replicas"]}
            # fleet total must equal the sum of what each replica
            # exports for itself on /metrics
            want = int(_metric(base_a, "dsql_server_queries_total")
                       + _metric(base_b, "dsql_server_queries_total"))
            if ({"r-a", "r-b"} <= set(rows)
                    and rows["r-a"]["alive"] and rows["r-b"]["alive"]
                    and snap["totals"]["serverQueries"] == want):
                break
            if time.time() > deadline:
                return fail(f"/v1/fleet never reconciled: totals="
                            f"{snap['totals']} want serverQueries={want}")
            time.sleep(0.3)
        if rows["r-a"]["pid"] != eng_a["pid"] or \
                rows["r-b"]["pid"] != eng_b["pid"]:
            return fail(f"heartbeat pids disagree with /v1/engine: {rows}")
        if snap["totals"]["warmServes"] < 1:
            return fail(f"fleet totals show no warm serves: "
                        f"{snap['totals']}")
        snap_b, _ = _req(f"{base_b}/v1/fleet")
        if {r["replica"] for r in snap_b["replicas"]} != set(rows):
            return fail("replicas disagree on the registry")
        print(f"ok registry: 2 replicas alive, fleet serverQueries="
              f"{snap['totals']['serverQueries']}, warmServes="
              f"{snap['totals']['warmServes']:.0f}")

        # -- 3. one trace stitched across replicas ------------------------
        rows_ev = [e for e in fleet.merged_events_rows()
                   if e.get("trace") == "fleet-smoke-trace"]
        rids = {e["replica"] for e in rows_ev}
        if rids != {"r-a", "r-b"}:
            return fail(f"trace not stitched across replicas: {rids}")
        if [e["unix"] for e in rows_ev] != \
                sorted(e["unix"] for e in rows_ev):
            return fail("merged trace events out of timestamp order")
        # and over the wire with the composite cursor
        with urllib.request.urlopen(
                f"{base_a}/v1/events?fleet=1&limit=5000",
                timeout=60) as r:
            cur = r.headers["X-DSQL-Cursor"]
            wire = [json.loads(x) for x in r.read().splitlines() if x]
        wire_rids = {e["replica"] for e in wire
                     if e.get("trace") == "fleet-smoke-trace"}
        if wire_rids != {"r-a", "r-b"} or ":" not in cur:
            return fail(f"/v1/events?fleet=1 not merged: {wire_rids} "
                        f"cursor={cur!r}")
        print(f"ok trace: fleet-smoke-trace spans {sorted(rids)} in "
              f"{len(rows_ev)} merged events, cursor {cur!r}")

        # -- 4. /metrics replica label ------------------------------------
        for base, rid in ((base_a, "r-a"), (base_b, "r-b")):
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=60) as r:
                lines = [ln for ln in r.read().decode().splitlines()
                         if ln and not ln.startswith("#")]
            tag = f'replica="{rid}"'
            if not lines or not all(tag in ln for ln in lines):
                bad = [ln for ln in lines if tag not in ln][:3]
                return fail(f"unlabeled series on {rid}: {bad}")
        print(f"ok metrics: every series labeled, {len(lines)} on r-b")
    finally:
        for p in (proc_a, proc_b):
            if p is not None:
                p.terminate()
        for p in (proc_a, proc_b):
            if p is not None:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()

    # -- 5. disarmed baseline: zero imports, 404, label-free wire --------
    child_code = (
        "import json, sys, urllib.error, urllib.request\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3, 4]})\n"
        "r1 = c.sql('SELECT SUM(a) AS s FROM t').to_pylist()\n"
        "assert r1 == [[10]], r1\n"
        "assert 'dask_sql_tpu.runtime.fleet' not in sys.modules, \\\n"
        "    'fleet imported with DSQL_FLEET_DIR unset'\n"
        "srv = c.run_server(host='127.0.0.1', port=0, blocking=False)\n"
        "base = f'http://127.0.0.1:{srv.server_port}'\n"
        "with urllib.request.urlopen(base + '/v1/statement'.replace("
        "'/v1/statement', '/metrics')) as r:\n"
        "    m = r.read().decode()\n"
        "assert 'replica=' not in m, 'replica label leaked while off'\n"
        "try:\n"
        "    urllib.request.urlopen(base + '/v1/fleet')\n"
        "    raise SystemExit('/v1/fleet served while disarmed')\n"
        "except urllib.error.HTTPError as e:\n"
        "    assert e.code == 404, e.code\n"
        "req = urllib.request.Request(base + '/v1/statement',\n"
        "                             data=b'SELECT SUM(a) AS s FROM t')\n"
        "with urllib.request.urlopen(req) as r:\n"
        "    p = json.loads(r.read())\n"
        "while 'nextUri' in p:\n"
        "    with urllib.request.urlopen(p['nextUri']) as r:\n"
        "        p = json.loads(r.read())\n"
        "assert p['data'] == [[10]], p\n"
        "assert 'replica' not in p['stats'], p['stats']\n"
        "assert 'dask_sql_tpu.runtime.fleet' not in sys.modules\n"
        "srv.shutdown()\n"
        "print('child ok')\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", child_code], env=env,
                          capture_output=True, timeout=600)
    if proc.returncode != 0 or b"child ok" not in proc.stdout:
        return fail(f"disarmed-baseline child: "
                    f"{proc.stderr.decode()[-800:]}")
    print("ok disarmed: zero fleet imports, /v1/fleet 404, "
          "label-free metrics, identical results")

    print("fleet obs smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
