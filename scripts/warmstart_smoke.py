#!/usr/bin/env python
"""Warm-start smoke gate: the program store + tiered execution must work.

Run by scripts/ci_local.sh (mirroring cache_smoke.py / sched_smoke.py):

    python scripts/warmstart_smoke.py

Asserts, across REAL process boundaries:

  1. a populate process (tiering off, store armed) compiles its queries
     and persists every stage program (``program_store_stores`` > 0);
  2. a FRESH process pointed at the populated ``DSQL_PROGRAM_STORE``
     answers the same queries with ZERO XLA compiles
     (``compiles == 0``, ``program_store_hits`` > 0) and byte-identical
     results — the restart-warm guarantee;
  3. tiered execution: with an EMPTY store and a slowed compile, the very
     first arrival of an uncompiled query returns the oracle-correct
     answer on the eager tier (``served_eager_while_compiling`` >= 1)
     without blocking on stage compilation, and stays under an
     eager-tier latency bound; the background compile then lands and the
     next arrival runs compiled.

Exit 0 on success — if cross-process warm starts silently rot (digests
drift, fingerprints stop matching, the tier gate stops firing), this gate
fails loudly.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DSQL_RESULT_CACHE_MB", "0")
os.environ.setdefault("DSQL_MAX_CONCURRENT_QUERIES", "0")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = 120_000

QUERIES = [
    # single-program aggregate
    "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY k ORDER BY k",
    # join + group-by: with DSQL_STAGE_HEAVY=1 this runs as a stage GRAPH,
    # so the warm process must hit the store once per stage program
    "SELECT d.name, SUM(t.v) AS s FROM t JOIN d ON t.k = d.k "
    "GROUP BY d.name ORDER BY d.name",
]


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _write_data(data_dir: str) -> None:
    import numpy as np
    import pandas as pd

    rng = np.random.RandomState(7)
    pd.DataFrame({
        "k": rng.randint(0, 32, N),
        "v": rng.rand(N),
    }).to_feather(os.path.join(data_dir, "t.feather"))
    pd.DataFrame({
        "k": np.arange(32),
        "name": [f"grp{i % 8}" for i in range(32)],
    }).to_feather(os.path.join(data_dir, "d.feather"))


def _phase_main(phase: str) -> int:
    """Child body: run QUERIES, print one JSON line of results+counters."""
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.runtime import telemetry as tel

    data_dir = os.environ["WARMSTART_DATA"]
    c = Context()
    for name in ("t", "d"):
        c.create_table(name, pd.read_feather(
            os.path.join(data_dir, f"{name}.feather")))
    results = {}
    for i, q in enumerate(QUERIES):
        results[str(i)] = c.sql(q, return_futures=False).to_dict("list")
    snap = tel.REGISTRY.counters()
    print("WARMSTART_JSON " + json.dumps({
        "results": results,
        "compiles": snap["compiles"],
        "stores": snap["program_store_stores"],
        "hits": snap["program_store_hits"],
        "rejects": snap["program_store_rejects"],
        "errors": snap["program_store_errors"],
    }))
    return 0


def _run_phase(phase: str, env: dict) -> dict:
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), f"--phase={phase}"],
        capture_output=True, text=True, env=env, timeout=420)
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-3000:])
        raise RuntimeError(f"{phase} phase exited rc={r.returncode}")
    for line in r.stdout.splitlines():
        if line.startswith("WARMSTART_JSON "):
            return json.loads(line[len("WARMSTART_JSON "):])
    sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
    raise RuntimeError(f"{phase} phase emitted no result line")


def _check_tiered_first_arrival() -> int:
    """In-process: empty store, slowed compile — the first arrival must be
    served on the eager tier without blocking on the build."""
    import pandas as pd

    from dask_sql_tpu import Context
    from dask_sql_tpu.physical import compiled
    from dask_sql_tpu.runtime import telemetry as tel

    os.environ["DSQL_TIERED"] = "1"
    os.environ.pop("DSQL_PROGRAM_STORE", None)

    data_dir = os.environ["WARMSTART_DATA"]
    frame = pd.read_feather(os.path.join(data_dir, "t.feather"))
    c = Context()
    c.create_table("t", frame)
    q = "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY k ORDER BY k"
    oracle = (frame.groupby("k").agg(s=("v", "sum"), n=("v", "size"))
              .reset_index().sort_values("k", ignore_index=True))

    # eager-tier latency baseline for the bound below
    os.environ["DSQL_COMPILE"] = "0"
    t0 = time.perf_counter()
    c.sql(q, return_futures=False)
    eager_sec = time.perf_counter() - t0
    del os.environ["DSQL_COMPILE"]

    delay_s = 5.0
    real_build = compiled._build

    def slow_build(*a, **k):
        time.sleep(delay_s)
        return real_build(*a, **k)

    compiled._build = slow_build
    try:
        c0 = tel.REGISTRY.counters()
        t0 = time.perf_counter()
        out = c.sql(q, return_futures=False)
        first_sec = time.perf_counter() - t0
        served = tel.REGISTRY.get("served_eager_while_compiling") \
            - c0["served_eager_while_compiling"]
        bg_done_at_return = tel.REGISTRY.get("background_compiles_done") \
            - c0["background_compiles_done"]
        if served < 1:
            return fail("first arrival was not served on the eager tier")
        if bg_done_at_return:
            return fail("background compile finished before the eager "
                        "answer returned — the tier gate did not overlap")
        if first_sec >= delay_s:
            return fail(f"first arrival ({first_sec:.2f}s) blocked on the "
                        f"{delay_s:.0f}s compile")
        bound = max(3.0 * eager_sec + 2.0, 4.0)
        if first_sec > bound:
            return fail(f"first arrival {first_sec:.2f}s exceeds the "
                        f"eager-tier bound {bound:.2f}s "
                        f"(eager baseline {eager_sec:.2f}s)")
        got = out.sort_values("k", ignore_index=True)
        if not (got["k"].tolist() == oracle["k"].tolist()
                and all(abs(a - b) < 1e-6 for a, b in
                        zip(got["s"], oracle["s"]))):
            return fail("eager-tier answer does not match the oracle")
        # the background compile must land; the next arrival runs compiled
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if tel.REGISTRY.get("background_compiles_done") \
                    - c0["background_compiles_done"] >= 1:
                break
            time.sleep(0.1)
        else:
            return fail("background compile never landed")
    finally:
        compiled._build = real_build
    c1 = tel.REGISTRY.counters()
    c.sql(q, return_futures=False)
    served2 = tel.REGISTRY.get("served_eager_while_compiling") \
        - c1["served_eager_while_compiling"]
    hits = tel.REGISTRY.get("hits") - c1["hits"]
    if served2 != 0 or hits < 1:
        return fail(f"second arrival did not run compiled "
                    f"(served_eager={served2}, hits={hits})")
    print(f"tiered: first arrival {first_sec:.2f}s on the eager tier "
          f"(eager baseline {eager_sec:.2f}s, compile delayed {delay_s:.0f}s"
          f"); second arrival compiled")
    return 0


def main() -> int:
    workdir = tempfile.mkdtemp(prefix="warmstart_smoke_")
    data_dir = os.path.join(workdir, "data")
    store_dir = os.path.join(workdir, "programs")
    os.makedirs(data_dir)
    os.environ["WARMSTART_DATA"] = data_dir
    _write_data(data_dir)

    base_env = dict(os.environ,
                    JAX_PLATFORMS="cpu",
                    WARMSTART_DATA=data_dir,
                    DSQL_PROGRAM_STORE=store_dir,
                    DSQL_RESULT_CACHE_MB="0",
                    DSQL_MAX_CONCURRENT_QUERIES="0",
                    DSQL_TIERED="0",
                    DSQL_STAGE_HEAVY="1")
    base_env.pop("DSQL_FAULT_INJECT", None)

    print("== populate process (cold store) ==")
    t0 = time.perf_counter()
    populate = _run_phase("populate", base_env)
    print(f"populate: compiles={populate['compiles']} "
          f"stores={populate['stores']} ({time.perf_counter() - t0:.1f}s)")
    if populate["compiles"] < 1:
        return fail("populate process compiled nothing")
    if populate["stores"] < populate["compiles"]:
        return fail(f"only {populate['stores']} of {populate['compiles']} "
                    "compiled programs were persisted")

    print("== warm process (fresh interpreter, populated store) ==")
    t0 = time.perf_counter()
    warm = _run_phase("warm", base_env)
    warm_sec = time.perf_counter() - t0
    print(f"warm: compiles={warm['compiles']} hits={warm['hits']} "
          f"({warm_sec:.1f}s)")
    if warm["compiles"] != 0:
        return fail(f"warm process paid {warm['compiles']} XLA compiles — "
                    "the store did not serve it")
    if warm["hits"] < 1:
        return fail("warm process recorded no program_store_hits")
    if warm["rejects"] or warm["errors"]:
        return fail(f"warm process saw rejects={warm['rejects']} "
                    f"errors={warm['errors']}")
    if warm["results"] != populate["results"]:
        return fail("warm-process results differ from populate-process "
                    "results")

    print("== tiered first arrival (empty store, slowed compile) ==")
    rc = _check_tiered_first_arrival()
    if rc:
        return rc

    print("warmstart smoke OK")
    return 0


if __name__ == "__main__":
    phase = next((a.split("=", 1)[1] for a in sys.argv[1:]
                  if a.startswith("--phase=")), None)
    if phase:
        sys.exit(_phase_main(phase))
    sys.exit(main())
