#!/usr/bin/env python
"""Watchtower smoke gate: trace IDs, the event bus, and SLO burn rates.

Run by scripts/ci_local.sh (mirroring scripts/profile_smoke.py):

    python scripts/events_smoke.py

With ``DSQL_EVENTS=1`` armed the gate proves

  1. one trace ID round-trips client -> server wire -> span tree ->
     flight-recorder envelope -> ``system.events`` — including a query
     run in a CHILD process against the shared history/events files;
  2. ``GET /v1/events`` streams the correlated events with a working
     cursor;
  3. a deliberately slow query (1 ms interactive objective) trips the
     interactive burn-rate gauge and the ``slo`` section on
     ``GET /v1/engine`` flags the breach;
  4. the disabled path is ZERO-cost: a child process with
     ``DSQL_EVENTS=0`` never imports ``runtime.events``, answers
     without trace headers, serves the generic 404 on ``/v1/events``,
     and returns bit-identical query results.

Exit 0 on success.
"""
import json
import os
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["DSQL_EVENTS"] = "1"
os.environ["DSQL_ADAPTIVE"] = "0"
os.environ.setdefault("DSQL_TIERED", "0")

_TMP = tempfile.mkdtemp(prefix="dsql_events_")
os.environ["DSQL_EVENTS_FILE"] = os.path.join(_TMP, "events.jsonl")
os.environ["DSQL_HISTORY_FILE"] = os.path.join(_TMP, "history.jsonl")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import events as ev  # noqa: E402
from dask_sql_tpu.runtime import flight_recorder as fr  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _req(url, body=None, headers=None):
    req = urllib.request.Request(
        url, data=body.encode() if body is not None else None,
        headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read() or b"null"), dict(r.headers)


def _finish(payload):
    while "nextUri" in payload:
        payload, _ = _req(payload["nextUri"])
    return payload


def main() -> int:
    ctx = Context()
    ctx.create_table("t", {"a": list(range(16))})
    srv = ctx.run_server(host="127.0.0.1", port=0, blocking=False)
    base = f"http://127.0.0.1:{srv.server_port}"
    try:
        # -- 1. end-to-end trace correlation ---------------------------------
        payload, hdrs = _req(f"{base}/v1/statement",
                             "SELECT SUM(a) AS s FROM t",
                             headers={"X-DSQL-Trace": "smoke-trace-1"})
        if hdrs.get("X-DSQL-Trace") != "smoke-trace-1":
            return fail(f"POST did not echo the trace header: {hdrs}")
        final = _finish(payload)
        if final.get("data") != [[120]]:
            return fail(f"wrong result: {final}")
        if final["stats"].get("traceId") != "smoke-trace-1":
            return fail(f"wire stats missing traceId: {final['stats']}")
        envs = [e for e in fr.read_events(kind="query")
                if e.get("trace") == "smoke-trace-1"]
        if not envs:
            return fail("flight-recorder envelope missing the trace ID")
        report = tel.last_report()  # server ran in-process worker threads
        types = {e["type"] for e in ev._read_file(
            os.environ["DSQL_EVENTS_FILE"])
            if e.get("trace") == "smoke-trace-1"}
        if not {"query.begin", "query.done"} <= types:
            return fail(f"bus events incomplete for the trace: {types}")
        print("ok trace: wire + envelope + bus agree on smoke-trace-1"
              + (f" (report {report.trace_id})"
                 if report is not None and report.trace_id else ""))

        # child process: same files, pinned trace ID, correlated from here
        child = (
            "from dask_sql_tpu import Context\n"
            "c = Context()\n"
            "c.create_table('t', {'a': [7, 8, 9]})\n"
            "assert c.sql('SELECT SUM(a) AS s FROM t'"
            ").to_pylist() == [[24]]\n"
        )
        env = dict(os.environ, DSQL_TRACE_ID="smoke-xproc-2",
                   DSQL_MAX_CONCURRENT_QUERIES="0",
                   DSQL_RESULT_CACHE_MB="0")
        proc = subprocess.run([sys.executable, "-c", child], env=env,
                              capture_output=True, timeout=600)
        if proc.returncode != 0:
            return fail(f"child query: {proc.stderr.decode()[-500:]}")
        rows = ctx.sql("SELECT count(*) AS n FROM system.events "
                       "WHERE trace = 'smoke-xproc-2'",
                       return_futures=False)
        n = int(rows["n"][0])
        if n < 2:
            return fail(f"system.events joined {n} child rows, want >= 2")
        xenvs = [e for e in fr.read_events(kind="query")
                 if e.get("trace") == "smoke-xproc-2"]
        if len(xenvs) != 1 or xenvs[0]["pid"] == os.getpid():
            return fail(f"child envelope wrong: {xenvs}")
        print(f"ok cross-process: child pid {xenvs[0]['pid']} correlated "
              f"via system.events ({n} rows)")

        # -- 2. /v1/events cursor stream -------------------------------------
        with urllib.request.urlopen(f"{base}/v1/events?cursor=0&limit=999",
                                    timeout=60) as r:
            cursor = int(r.headers["X-DSQL-Cursor"])
            lines = [json.loads(l) for l in r.read().splitlines() if l]
        if cursor <= 0 or not any(e["type"] == "query.done"
                                  for e in lines):
            return fail(f"/v1/events stream dead: cursor={cursor}")
        with urllib.request.urlopen(f"{base}/v1/events?cursor={cursor}",
                                    timeout=60) as r:
            if r.read() != b"":
                return fail("cursor resume returned stale events")
        print(f"ok /v1/events: {len(lines)} events, cursor {cursor}")

        # -- 3. slow query trips the interactive burn gauge ------------------
        os.environ["DSQL_SLO_INTERACTIVE_MS"] = "1"   # everything breaches
        try:
            payload, _ = _req(f"{base}/v1/statement",
                              "SELECT a, SUM(a) AS s FROM t GROUP BY a")
            _finish(payload)
        finally:
            del os.environ["DSQL_SLO_INTERACTIVE_MS"]
        burn = tel.REGISTRY.gauges().get("slo_burn_fast_interactive", 0.0)
        if burn <= 2.0:
            return fail(f"slow query did not trip the burn gauge: {burn}")
        snap, _ = _req(f"{base}/v1/engine")
        slo = snap.get("slo", {})
        if not slo.get("enabled"):
            return fail(f"/v1/engine slo section missing: {sorted(snap)}")
        inter = [r for r in slo["classes"]
                 if r["class"] == "interactive"][0]
        if inter["breaches"] < 1:
            return fail(f"slo section shows no breach: {inter}")
        kinds = {a["kind"] for a in slo["anomalies"]}
        print(f"ok slo: burn_fast={burn:.1f} breaches={inter['breaches']} "
              f"anomalies={sorted(kinds) or 'none'}")
    finally:
        srv.shutdown()
        ctx.server = None

    # -- 4. disabled path: zero imports, no headers, identical results ------
    child_code = (
        "import json, sys, urllib.request\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3, 4]})\n"
        "r1 = c.sql('SELECT SUM(a) AS s FROM t').to_pylist()\n"
        "assert r1 == [[10]], r1\n"
        "assert 'dask_sql_tpu.runtime.events' not in sys.modules, \\\n"
        "    'events imported with DSQL_EVENTS=0'\n"
        "srv = c.run_server(host='127.0.0.1', port=0, blocking=False)\n"
        "base = f'http://127.0.0.1:{srv.server_port}'\n"
        "req = urllib.request.Request(base + '/v1/statement',\n"
        "    data=b'SELECT SUM(a) AS s FROM t',\n"
        "    headers={'X-DSQL-Trace': 'must-be-ignored'})\n"
        "with urllib.request.urlopen(req) as r:\n"
        "    p = json.loads(r.read())\n"
        "    assert 'X-DSQL-Trace' not in r.headers, dict(r.headers)\n"
        "while 'nextUri' in p:\n"
        "    with urllib.request.urlopen(p['nextUri']) as r:\n"
        "        p = json.loads(r.read())\n"
        "assert p['data'] == [[10]], p\n"
        "assert 'traceId' not in p['stats'], p['stats']\n"
        "try:\n"
        "    urllib.request.urlopen(base + '/v1/events')\n"
        "    raise SystemExit('/v1/events served while disabled')\n"
        "except urllib.error.HTTPError as e:\n"
        "    assert e.code == 404, e.code\n"
        "assert 'dask_sql_tpu.runtime.events' not in sys.modules\n"
        "srv.shutdown()\n"
        "print('child ok')\n"
    )
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DSQL_")}
    env["JAX_PLATFORMS"] = "cpu"
    env["DSQL_EVENTS"] = "0"
    proc = subprocess.run([sys.executable, "-c", child_code], env=env,
                          capture_output=True, timeout=600)
    if proc.returncode != 0 or b"child ok" not in proc.stdout:
        return fail(f"disabled-path child: {proc.stderr.decode()[-800:]}")
    print("ok disabled path: zero events imports, no trace surface, "
          "identical results")

    print("events smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
