#!/usr/bin/env bash
# Tier-1 verify gate — the ROADMAP.md command, verbatim, plus the
# fault-injection smoke.  CI, the driver and humans must all run the SAME
# invocation or "tier-1 green" means different things to each of them.
cd "$(dirname "$0")/.."
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# Fault-injection smoke (runtime/faults.py): a TPC-H subset with the first
# compile of every query sabotaged must still return oracle-correct results
# via the resilience ladder (retry/degrade).  Runs only when the suite
# itself passed, so a red suite keeps its own diagnosis.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 600 env JAX_PLATFORMS=cpu DSQL_FAULT_INJECT=compile:1 \
    python scripts/fault_smoke.py || rc=1
fi
exit $rc
