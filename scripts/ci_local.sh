#!/usr/bin/env bash
# Executable form of .github/workflows/test.yml for environments without a
# GitHub runner (this image). Runs the same four jobs in sequence:
#   1. native parser build from source + load check
#   2. full suite, single device
#   3. distributed suites on the 8-device virtual CPU mesh
#   4. bare `pip install .` import smoke test (native fallback path)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "=== [1/4] native build ==="
make -C native clean all
python -c "from dask_sql_tpu.native import available; assert available()"

echo "=== [2/4] full suite (single device, process-isolated groups) ==="
# Grouped into separate pytest processes: a crash in one group fails THAT
# group loudly instead of silently truncating the whole run, and per-process
# memory stays bounded (the one-process 565-test run peaked at ~4.4 GB and
# segfaulted in r2).  set -e aborts on the first failing group.
python -m pytest tests/unit -q
python -m pytest tests/integration \
    --ignore=tests/integration/test_tpch.py \
    --ignore=tests/integration/test_tpch_mesh.py \
    --ignore=tests/integration/test_streaming.py \
    --ignore=tests/integration/test_distributed.py \
    --ignore=tests/integration/test_compiled.py \
    --ignore=tests/integration/test_pandas_oracle.py -q
python -m pytest tests/integration/test_compiled.py \
                 tests/integration/test_streaming.py -q
python -m pytest tests/integration/test_tpch.py \
                 tests/integration/test_pandas_oracle.py -q

echo "=== [2b] fault-injection smoke (resilience ladder) ==="
# the first compile of every query is sabotaged (runtime/faults.py); the
# ladder must retry/degrade to the same oracle-correct answers
DSQL_FAULT_INJECT=compile:1 python scripts/fault_smoke.py

echo "=== [2c] observability smoke (telemetry layer) ==="
# three queries with tracing armed: well-formed QueryReports, annotated
# EXPLAIN ANALYZE, non-empty advancing /metrics, chrome-trace exports
python scripts/obs_smoke.py

echo "=== [2d] result-cache smoke (reuse layer) ==="
# a repeated query must hit (execute >=5x faster), DDL on a referenced
# table must invalidate, and DSQL_RESULT_CACHE_MB=0 must disable cleanly
python scripts/cache_smoke.py

echo "=== [2e] scheduler smoke (workload manager) ==="
# 8 mixed-priority queries through a 2-slot scheduler: none lost,
# interactive p50 queue time < batch p50, admission counters reconcile,
# and DSQL_MAX_CONCURRENT_QUERIES=0 restores pre-subsystem behavior
python scripts/sched_smoke.py

echo "=== [2f] chaos soak (failure-domain recovery) ==="
# 45 s of randomized probabilistic faults (p=0.05, every site) under 4
# concurrent mixed-priority clients: zero wrong results, zero lost/hung
# queries, admission counters reconcile, engine healthy afterwards
python scripts/chaos_soak.py --budget-s 45

echo "=== [2g] warm-start smoke (tiered execution + program store) ==="
# a fresh process pointed at a populated DSQL_PROGRAM_STORE must answer
# previously-seen queries with ZERO XLA compiles; with an empty store and
# a slowed compile, the first arrival must answer on the eager tier
# without blocking, then run compiled on the next arrival
python scripts/warmstart_smoke.py

echo "=== [2h] stats smoke (adaptive operator selection) ==="
# dense direct-index must beat forced hash on a 2M-row dense-key
# aggregate, all forced variants must agree, the stats join reorder must
# attach the fact table last, and DSQL_ADAPTIVE=0 must restore baseline
python scripts/stats_smoke.py

echo "=== [2i] shard smoke (explicit SPMD multi-chip executor) ==="
# Q1/Q3/Q6 sharded over the 8-device mesh must match the single-device
# answers with the spmd_* counters proving the sharded path served them
# (exchange/partial-agg collectives, nonzero exchange bytes on Q3), a
# zero broadcast cap must force the hash-partition exchange join, and
# DSQL_MESH=0 must restore the baseline with no spmd counters moving
python scripts/shard_smoke.py

echo "=== [2j] out-of-core smoke (spill manager + grace-hash joins) ==="
# TPC-H-shaped queries over chunked tables under a tiny device budget:
# Q1/Q6 shapes stream, a Q3 shape grace-hash-partitions through the spill
# store (spill_partitions > 0, runs freed, device occupancy bounded), and
# DSQL_SPILL_MB=0 restores the pre-spill StreamingUnsupported baseline
python scripts/ooc_smoke.py

echo "=== [2k] profile smoke (device-level query profiler) ==="
# EXPLAIN PROFILE over the 8-device mesh must render nonzero per-stage
# XLA cost, per-device HBM rows, sane shard skew and collective bytes by
# kind; the cost-model estimate rung must close; DSQL_PROFILE=0 must
# never even import the profiler
python scripts/profile_smoke.py

echo "=== [2l] perf sentinel (bench regression gate) ==="
# the committed bench trajectory must sit inside the tolerance bands of
# the published baseline, and the sentinel must prove it still catches a
# doctored 2x regression
python scripts/perf_sentinel.py
python scripts/perf_sentinel.py --self-test

echo "=== [2m] matview smoke (incremental view maintenance) ==="
# a 1k-row append into a 1M-row base must refresh the maintained view
# >=5x faster than recomputing the defining query, stay pandas-oracle
# exact across appends and an overwrite, reconcile the mv_* counters,
# and DSQL_MV=0 must restore pre-subsystem behavior
python scripts/mv_smoke.py

echo "=== [2n] events smoke (watchtower: traces, bus, SLO burn) ==="
# one trace ID must round-trip client -> wire -> span tree -> envelope ->
# system.events (a child process included), /v1/events must stream with
# a working cursor, a deliberately slow query must trip the interactive
# burn-rate gauge, and DSQL_EVENTS=0 must never even import the bus
python scripts/events_smoke.py

echo "=== [2o] param smoke (parameterized plan identity) ==="
# 50 literal variants of one query shape must compile at most twice with
# a >90% plan-cache hit rate and pandas-oracle parity; a fresh process
# must serve a never-seen literal of a stored shape with zero compiles;
# DSQL_PARAM_PLANS=0 must restore value-baked program identity
python scripts/param_smoke.py

echo "=== [2p] fleet smoke (result paging + tenant quotas + kill switches) ==="
# a ~1M-row result must page through the spool behind a real nextUri with
# the peak single response under 10% of the whole, a noisy tenant on a
# 2-slot server must be throttled (429 + honest Retry-After) while a quiet
# tenant loses zero queries, a client that disconnects mid-pagination must
# be fully reaped within DSQL_RESULT_TTL_S (no /v1/engine occupancy), and
# DSQL_RESULT_PAGE_ROWS=0 / DSQL_TENANCY=0 must restore the pre-armor wire
python scripts/fleet_smoke.py

echo "=== [2q] fleet obs smoke (replica registry + shared warmth) ==="
# two real server replicas on one shared DSQL_FLEET_DIR + program store:
# replica B must serve replica A's query shape with ZERO compiles,
# /v1/fleet must reconcile with each replica's own /v1/engine + /metrics,
# one trace ID must stitch across both replicas in the merged
# system.events stream, and unsetting DSQL_FLEET_DIR must restore the
# label-free baseline wire exactly (fleet module never imported)
python scripts/fleet_obs_smoke.py

echo "=== [2r] autopilot smoke (closed loop: watchtower -> optimizer) ==="
# a shifting workload must converge unattended: the top view candidate
# auto-materialized within 3 queries and served oracle-exact across an
# append, the cold view dropped with its budget freed, a skewed grace
# join re-planned via a journaled hint that measures faster on the next
# run, everything visible in system.autopilot, and DSQL_AUTOPILOT=0 a
# bit-for-bit silent baseline
python scripts/autopilot_smoke.py

echo "=== [2s] ingest smoke (WAL-backed continuous ingestion) ==="
# sustained appends must keep delta-join and COUNT(DISTINCT) views
# oracle-exact with every refresh incremental (>=5x faster than the
# defining recompute), readers must never see a partial batch or two
# prefixes in one query, kill -9 must lose zero acked batches (WAL
# replay), and DSQL_INGEST=0 / an unset dir must never even import the
# ingest module
python scripts/ingest_smoke.py

echo "=== [3/4] mesh suites (8 virtual devices) + 2-process multihost ==="
python -m pytest tests/integration/test_distributed.py \
                 tests/integration/test_tpch_mesh.py \
                 tests/integration/test_spmd_executor.py \
                 tests/integration/test_multihost.py -q

echo "=== [4/4] bare install smoke ==="
TMPDIR=$(mktemp -d)
# --no-build-isolation/--no-deps: the zero-egress image can fetch neither
# the isolated build env's setuptools nor the install_requires; the venv
# already carries both, and the smoke below resolves deps from the venv
pip install --quiet --no-build-isolation --no-deps \
    --target "$TMPDIR/site" . >/dev/null
(cd /tmp && PYTHONPATH="$TMPDIR/site" python - <<'EOF'
import jax; jax.config.update('jax_platforms', 'cpu')
import pandas as pd
from dask_sql_tpu import Context
c = Context()
c.create_table('t', pd.DataFrame({'a': [1, 2, 3]}))
out = c.sql('SELECT SUM(a) AS s FROM t', return_futures=False)
assert int(out['s'][0]) == 6, out
print('bare install OK')
EOF
)
rm -rf "$TMPDIR"
echo "=== CI green ==="
