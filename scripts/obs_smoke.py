#!/usr/bin/env python
"""Observability smoke gate: telemetry must actually observe real queries.

Run by scripts/ci_local.sh (mirroring scripts/fault_smoke.py):

    python scripts/obs_smoke.py

Three TPC-H queries run with tracing armed (slow-query log at 0 ms so every
query logs, chrome-trace export into a temp dir); the gate asserts

  1. every query attached a well-formed QueryReport (wall > 0, phase sums
     bounded by the wall, rows_out matching the result);
  2. EXPLAIN ANALYZE annotates every executed plan node with wall-time and
     row counts;
  3. ``GET /metrics`` on a live server is non-empty prometheus text whose
     counters cover the engine's work (compiles+hits >= query count) and
     never decrease across queries;
  4. the chrome-trace export produced one well-formed JSON per query;
  5. the flight recorder survives the process boundary: queries run in a
     CHILD process land in ``DSQL_HISTORY_FILE`` and a fresh Context here
     reads them back through ``SELECT ... FROM system.queries``;
  6. ``GET /v1/engine`` reports a live query MID-FLIGHT (a sleeping UDF
     holds one open while the gate polls);
  7. the estimate feedback loop closes: a repeat run reserves from
     measured history (``estimate_from_history`` advances).

Exit 0 on success — if the telemetry wiring silently rots (spans not
opened, counters not routed, endpoint dead), this gate fails loudly.
"""
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this gate asserts SYNCHRONOUS compile behavior; tiered execution
# (eager-first + background compile, on by default) is gated by
# scripts/warmstart_smoke.py instead
os.environ.setdefault("DSQL_TIERED", "0")
TRACE_DIR = tempfile.mkdtemp(prefix="dsql_obs_")
os.environ["DSQL_CHROME_TRACE_DIR"] = TRACE_DIR
os.environ["DSQL_SLOW_QUERY_MS"] = "0"   # every query trips the slow log
# flight recorder armed for the whole gate: every query below leaves a
# persistent envelope + operator statistics (parts 5-7)
HIST_FILE = os.path.join(TRACE_DIR, "history.jsonl")
os.environ["DSQL_HISTORY_FILE"] = HIST_FILE

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.tpch import QUERIES, generate_tpch  # noqa: E402
from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

# agg-heavy (Q1), join+agg+topk (Q3), scan/filter (Q6): the same shape
# coverage the fault smoke uses
SUBSET = (1, 3, 6)
SF = 0.002


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def main() -> int:
    data = generate_tpch(SF)
    ctx = Context()
    for name, df in data.items():
        ctx.create_table(name, df)

    # -- 1. per-query reports ------------------------------------------------
    for qid in SUBSET:
        got = ctx.sql(QUERIES[qid], return_futures=False)
        rep = ctx.last_report
        if rep is None:
            return fail(f"q{qid}: no QueryReport attached")
        if rep.wall_ms <= 0:
            return fail(f"q{qid}: non-positive wall ({rep.wall_ms})")
        top = sum(rep.phases.get(k, 0.0)
                  for k in ("parse", "plan", "execute", "fetch"))
        if top > rep.wall_ms + 1e-6:
            return fail(f"q{qid}: phase sum {top:.3f} > wall "
                        f"{rep.wall_ms:.3f}")
        if rep.rows_out != len(got):
            return fail(f"q{qid}: rows_out {rep.rows_out} != {len(got)}")
        print(f"ok q{qid}: report wall={rep.wall_ms:.1f}ms phases="
              f"{sorted(rep.phases)} counters={sorted(rep.counters)}")

    # -- 2. EXPLAIN ANALYZE --------------------------------------------------
    out = ctx.sql("EXPLAIN ANALYZE " + QUERIES[3], return_futures=False)
    plan_lines = [l for l in out["PLAN"] if not l.startswith("--")]
    bad = [l for l in plan_lines if "rows=" not in l or "time=" not in l]
    if not plan_lines or bad:
        return fail(f"EXPLAIN ANALYZE unannotated lines: {bad[:3]}")
    print(f"ok explain-analyze: {len(plan_lines)} annotated nodes")

    # -- 3. /metrics on a live server ----------------------------------------
    srv = ctx.run_server(host="127.0.0.1", port=0, blocking=False)
    try:
        base = f"http://127.0.0.1:{srv.server_port}"
        with urllib.request.urlopen(f"{base}/metrics") as r:
            ctype, text = r.headers.get("Content-Type", ""), \
                r.read().decode()
        if not text.strip():
            return fail("/metrics empty")
        if not ctype.startswith("text/plain"):
            return fail(f"/metrics content-type {ctype!r}")

        def val(name):
            for line in text.splitlines():
                if line.startswith(name + " "):
                    return float(line.rsplit(" ", 1)[1])
            raise AssertionError(name)

        work = val("dsql_compiles_total") + val("dsql_hits_total") \
            + val("dsql_fallbacks_total") + val("dsql_unsupported_total")
        if val("dsql_queries_total") < len(SUBSET) or work < 1:
            return fail("metrics do not cover the queries that ran")
        # monotonicity across another query
        before = val("dsql_queries_total")
        ctx.sql(QUERIES[6], return_futures=False)
        with urllib.request.urlopen(f"{base}/metrics") as r:
            text = r.read().decode()
        if val("dsql_queries_total") < before + 1:
            return fail("dsql_queries_total did not advance")
        print("ok /metrics: prometheus text, counters advancing")
    finally:
        srv.shutdown()
        ctx.server = None

    # -- 4. chrome traces ----------------------------------------------------
    traces = [f for f in os.listdir(TRACE_DIR) if f.endswith(".trace.json")]
    if len(traces) < len(SUBSET):
        return fail(f"expected >= {len(SUBSET)} chrome traces, found "
                    f"{len(traces)}")
    with open(os.path.join(TRACE_DIR, traces[0])) as f:
        blob = json.load(f)
    if not blob.get("traceEvents"):
        return fail("chrome trace has no events")
    print(f"ok chrome traces: {len(traces)} files")

    # -- 5. cross-process history via system.queries -------------------------
    from dask_sql_tpu.runtime import flight_recorder as fr
    n0 = len(fr.read_events(kind="query"))
    child_code = (
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('smoke_t', {'a': [1, 2, 3, 4]})\n"
        "c.sql('SELECT SUM(a) AS s FROM smoke_t')\n"
        "c.sql('SELECT COUNT(*) AS n FROM smoke_t')\n"
    )
    proc = subprocess.run([sys.executable, "-c", child_code],
                          env=dict(os.environ), capture_output=True,
                          timeout=600)
    if proc.returncode != 0:
        return fail(f"history child process died: {proc.stderr.decode()}")
    fresh = Context()  # no user tables: reads PURELY through system schema
    n1 = fresh.sql("SELECT count(*) AS n FROM system.queries"
                   ).to_pylist()[0][0]
    if n1 < n0 + 2:
        return fail(f"system.queries missed the child's queries "
                    f"({n0} -> {n1})")
    pids = {r[0] for r in fresh.sql(
        "SELECT DISTINCT pid FROM system.queries").to_pylist()}
    if not any(p != os.getpid() for p in pids):
        return fail("no cross-process pid in system.queries")
    print(f"ok system.queries: {n1} envelopes incl. child pid")

    # -- 6. /v1/engine mid-flight --------------------------------------------
    import numpy as np
    release = threading.Event()

    def slow_fn(x):
        release.set()
        time.sleep(1.5)
        return x.astype(np.float64)

    ctx.create_table("slow_t", {"a": np.arange(8, dtype=np.int64)})
    ctx.register_function(slow_fn, "slow_fn", [("x", np.int64)], np.float64)
    srv = ctx.run_server(host="127.0.0.1", port=0, blocking=False)
    try:
        base = f"http://127.0.0.1:{srv.server_port}"
        req = urllib.request.Request(
            f"{base}/v1/statement",
            data=b"SELECT SUM(slow_fn(a)) AS s FROM slow_t", method="POST")
        with urllib.request.urlopen(req) as r:
            payload = json.loads(r.read())
        if not release.wait(timeout=120):
            return fail("mid-flight UDF never started")
        with urllib.request.urlopen(f"{base}/v1/engine") as r:
            snap = json.loads(r.read())
        live = [a for a in snap.get("active", [])
                if "slow_fn" in a.get("query", "")]
        if not live:
            return fail(f"/v1/engine missed the live query: "
                        f"{snap.get('active')}")
        for key in ("scheduler", "memory", "cache", "history"):
            if key not in snap:
                return fail(f"/v1/engine payload missing {key!r}")
        deadline = time.time() + 120
        while "nextUri" in payload and time.time() < deadline:
            time.sleep(0.05)
            with urllib.request.urlopen(payload["nextUri"]) as r:
                payload = json.loads(r.read())
        if payload.get("data") != [[28.0]]:
            return fail(f"mid-flight query wrong result: "
                        f"{payload.get('data')}")
        print("ok /v1/engine: live query visible mid-flight")
    finally:
        srv.shutdown()
        ctx.server = None

    # -- 7. estimate feedback loop -------------------------------------------
    before = tel.REGISTRY.get("estimate_from_history")
    ctx.sql(QUERIES[1], return_futures=False)   # ran in part 1: history hit
    after = tel.REGISTRY.get("estimate_from_history")
    if after <= before:
        return fail("estimate_from_history did not advance on repeat run")
    ev = fr.read_events(kind="query")[-1]
    if ev.get("est_source") != "history":
        return fail(f"repeat run estimated from {ev.get('est_source')!r}, "
                    "not history")
    print(f"ok estimate feedback: estimate_from_history={after} "
          f"est={ev['est_bytes']}B measured={ev['measured_bytes']}B")

    print("observability smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
