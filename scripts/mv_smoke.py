#!/usr/bin/env python
"""Materialized-view smoke gate: O(delta) maintenance must be fast,
oracle-correct, and cleanly killable.

Run by scripts/ci_local.sh (mirroring cache_smoke.py / stats_smoke.py):

    python scripts/mv_smoke.py

Asserts, against a real Context on a 1M-row generated table:

  1. after a 1k-row append, the maintained refresh (partial-aggregate
     over the delta merged with cached state) is >= 5x faster than a
     full recompute of the defining query over the base table;
  2. the served view is pandas-oracle-exact across >= 3 append
     sequences AND after a base-table overwrite (the tombstone seam:
     a stale maintained view is never served);
  3. the mv_* telemetry counters reconcile with the observed refresh
     history (every append maintained incrementally, the overwrite and
     initial materialization recomputed in full);
  4. ``DSQL_MV=0`` restores pre-subsystem behavior: MV DDL raises a
     typed UserError, plain queries still answer oracle-correct, and
     no mv_* counter moves.

Exit 0 on success — if maintenance silently rots (deltas stop landing,
the state key drifts, refreshes degrade to recomputes), this gate
fails loudly.
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# synchronous compiles: the timing comparison must not race the
# background compile of the tiered executor
os.environ.setdefault("DSQL_TIERED", "0")
# maintained state is a result-cache tenant — the subsystem needs budget
os.environ["DSQL_RESULT_CACHE_MB"] = "256"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import result_cache as rc  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402
from dask_sql_tpu.runtime.resilience import UserError  # noqa: E402

N = 1_000_000
DELTA = 1_000
DEFINING = ("SELECT k, SUM(x) AS sx, COUNT(*) AS n, AVG(y) AS ay, "
            "MIN(x) AS mn, MAX(x) AS mx FROM t GROUP BY k")


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _frame(n: int, seed: int) -> pd.DataFrame:
    rng = np.random.RandomState(seed)
    return pd.DataFrame({
        "k": rng.randint(0, 100, n),
        "x": rng.rand(n) * 100,
        "y": rng.randint(0, 1000, n),
    })


def _oracle(frame: pd.DataFrame) -> pd.DataFrame:
    g = frame.groupby("k")
    return pd.DataFrame({
        "sx": g["x"].sum(), "n": g.size(), "ay": g["y"].mean(),
        "mn": g["x"].min(), "mx": g["x"].max(),
    }).reset_index().sort_values("k").reset_index(drop=True)


def _served(ctx: Context) -> pd.DataFrame:
    got = ctx.sql("SELECT * FROM v", return_futures=False)
    return got.sort_values("k").reset_index(drop=True).astype({"n": "int64"})


def _check(ctx: Context, base: pd.DataFrame, what: str):
    exp = _oracle(base).astype({"n": "int64"})
    got = _served(ctx)
    pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                  check_exact=False)
    print(f"ok oracle: {what} ({len(base)} base rows)")


def _mv_counters() -> dict:
    snap = tel.REGISTRY.counters()
    return {k: snap.get(k, 0) for k in
            ("mv_serves", "mv_refresh_incremental", "mv_refresh_full",
             "mv_deltas_recorded")}


def main() -> int:
    rc.get_cache().clear()
    ctx = Context()
    base = _frame(N, seed=1)
    ctx.create_table("t", base)

    c0 = _mv_counters()
    ctx.sql(f"CREATE MATERIALIZED VIEW v AS {DEFINING}")
    _check(ctx, base, "initial materialization")

    # -- 1. speed: maintained refresh vs full recompute --------------------
    # warm-up: the first refresh pays one-time XLA compiles for the
    # partial/merge plan shapes; the steady-state claim is about
    # maintenance work, not compiler latency
    warm = _frame(DELTA, seed=99)
    ctx.append_rows("t", warm)
    base = pd.concat([base, warm], ignore_index=True)
    ctx.sql("REFRESH MATERIALIZED VIEW v")
    ctx.sql(DEFINING, return_futures=False)

    delta = _frame(DELTA, seed=2)
    ctx.append_rows("t", delta)
    base = pd.concat([base, delta], ignore_index=True)
    t0 = time.perf_counter()
    ctx.sql("REFRESH MATERIALIZED VIEW v")
    refresh_sec = time.perf_counter() - t0
    t0 = time.perf_counter()
    recomputed = ctx.sql(DEFINING, return_futures=False)
    recompute_sec = time.perf_counter() - t0
    if len(recomputed) != base["k"].nunique():
        return fail("recompute control query returned wrong group count")
    if refresh_sec * 5 > recompute_sec:
        return fail(f"maintained refresh not >=5x faster: refresh="
                    f"{refresh_sec * 1e3:.1f}ms recompute="
                    f"{recompute_sec * 1e3:.1f}ms")
    print(f"ok speed: refresh={refresh_sec * 1e3:.1f}ms recompute="
          f"{recompute_sec * 1e3:.1f}ms "
          f"({recompute_sec / max(refresh_sec, 1e-9):.0f}x)")
    _check(ctx, base, "append #1 (timed)")

    # -- 2. oracle parity across further appends + an overwrite ------------
    for i in range(2, 4):
        delta = _frame(DELTA, seed=i + 1)
        ctx.append_rows("t", delta)
        base = pd.concat([base, delta], ignore_index=True)
        _check(ctx, base, f"append #{i}")

    base = _frame(200_000, seed=9)  # overwrite: brand-new, smaller base
    ctx.create_table("t", base)
    _check(ctx, base, "overwrite (tombstone seam)")

    # -- 3. counters reconcile ---------------------------------------------
    c1 = _mv_counters()
    moved = {k: c1[k] - c0[k] for k in c1}
    # 4 appends (warm-up + 3 checked) all maintained; initial build +
    # post-overwrite recompute are the only full refreshes
    if moved["mv_deltas_recorded"] != 4:
        return fail(f"expected 4 delta records, saw {moved}")
    if moved["mv_refresh_incremental"] != 4:
        return fail(f"expected 4 incremental refreshes, saw {moved}")
    if moved["mv_refresh_full"] != 2:
        return fail(f"expected 2 full refreshes (initial + overwrite), "
                    f"saw {moved}")
    if moved["mv_serves"] < 5:
        return fail(f"expected >=5 serves, saw {moved}")
    print(f"ok counters: {moved}")

    # -- 4. DSQL_MV=0 restores pre-subsystem behavior ----------------------
    os.environ["DSQL_MV"] = "0"
    try:
        off = Context()
        off_base = _frame(50_000, seed=11)
        off.create_table("t", off_base)
        try:
            off.sql(f"CREATE MATERIALIZED VIEW v AS {DEFINING}")
            return fail("CREATE MATERIALIZED VIEW accepted under DSQL_MV=0")
        except UserError:
            pass
        before = _mv_counters()
        got = off.sql(DEFINING, return_futures=False)
        got = got.sort_values("k").reset_index(drop=True)
        pd.testing.assert_frame_equal(
            got.astype({"n": "int64"}), _oracle(off_base).astype(
                {"n": "int64"}), check_dtype=False, check_exact=False)
        off.append_rows("t", _frame(100, seed=12))
        if _mv_counters() != before:
            return fail("mv_* counters moved under DSQL_MV=0")
    finally:
        os.environ.pop("DSQL_MV", None)
    print("ok disable: DSQL_MV=0 rejects DDL, answers match, no counters")

    print("materialized-view smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
