#!/usr/bin/env python
"""Parameterized-plan smoke gate: one compiled program per query shape.

Run by scripts/ci_local.sh (mirroring warmstart_smoke.py):

    python scripts/param_smoke.py

Asserts, in one process:

  1. 50 literal variants of ONE query shape compile at most twice
     (``compiles <= 2`` — one for the shape; headroom for a capacity
     escalation) with a plan-cache hit rate above 90%
     (``param_plan_hits / executions``);
  2. every variant matches the pandas oracle — hoisted literals must not
     change answers;
  3. ``DSQL_PARAM_PLANS=0`` restores value-baked program identity: the
     same variants each compile their own program and no ``param_*``
     counter moves — the kill switch is bit-for-bit;
  4. across a REAL process boundary: a fresh interpreter pointed at the
     populated ``DSQL_PROGRAM_STORE`` answers a NEVER-SEEN literal of the
     same shape with zero XLA compiles.

Exit 0 on success — if shape identity silently rots (fingerprints start
baking values again, the store stops serving cross-literal), this gate
fails loudly.
"""
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DSQL_RESULT_CACHE_MB", "0")
os.environ.setdefault("DSQL_MAX_CONCURRENT_QUERIES", "0")
os.environ.setdefault("DSQL_TIERED", "0")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = 60_000
VARIANTS = 50

QUERY = "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t WHERE v > {lit} GROUP BY k ORDER BY k"


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _frame():
    import numpy as np
    import pandas as pd

    rng = np.random.RandomState(11)
    return pd.DataFrame({"k": rng.randint(0, 16, N), "v": rng.rand(N)})


def _oracle(frame, lit):
    sub = frame[frame.v > lit]
    return (sub.groupby("k").agg(s=("v", "sum"), n=("v", "size"))
            .reset_index().sort_values("k", ignore_index=True))


def _literals():
    return [round(0.01 + 0.018 * i, 4) for i in range(VARIANTS)]


def _run_variants(c, frame):
    import pandas as pd

    from dask_sql_tpu.runtime import telemetry as tel

    c0 = tel.REGISTRY.counters()
    for lit in _literals():
        got = (c.sql(QUERY.format(lit=lit), return_futures=False)
               .sort_values("k", ignore_index=True))
        exp = _oracle(frame, lit)
        pd.testing.assert_frame_equal(got, exp, check_dtype=False,
                                      atol=1e-6, rtol=1e-6)
    now = tel.REGISTRY.counters()
    return {k: now[k] - c0.get(k, 0) for k in now}


def _phase_main(phase: str) -> int:
    """Child body: run one literal of the shape, print counters."""
    from dask_sql_tpu import Context
    from dask_sql_tpu.runtime import telemetry as tel

    lit = float(os.environ["PARAM_SMOKE_LIT"])
    c = Context()
    c.create_table("t", _frame())
    out = (c.sql(QUERY.format(lit=lit), return_futures=False)
           .sort_values("k", ignore_index=True))
    snap = tel.REGISTRY.counters()
    print("PARAMSMOKE_JSON " + json.dumps({
        "result": {"k": [int(x) for x in out["k"]],
                   "s": [round(float(x), 6) for x in out["s"]],
                   "n": [int(x) for x in out["n"]]},
        "compiles": snap["compiles"],
        "stores": snap["program_store_stores"],
        "hits": snap["program_store_hits"],
        "param_plan_hits": snap["param_plan_hits"],
    }))
    return 0


def _run_phase(lit: float, env: dict) -> dict:
    env = dict(env, PARAM_SMOKE_LIT=str(lit))
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--phase=child"],
        capture_output=True, text=True, env=env, timeout=420)
    if r.returncode != 0:
        sys.stderr.write(r.stderr[-3000:])
        raise RuntimeError(f"child exited rc={r.returncode}")
    for line in r.stdout.splitlines():
        if line.startswith("PARAMSMOKE_JSON "):
            return json.loads(line[len("PARAMSMOKE_JSON "):])
    sys.stderr.write(r.stdout[-2000:] + r.stderr[-2000:])
    raise RuntimeError("child emitted no result line")


def main() -> int:
    from dask_sql_tpu import Context

    frame = _frame()

    print(f"== {VARIANTS} literal variants, param plans ON ==")
    os.environ.pop("DSQL_PARAM_PLANS", None)
    c = Context()
    c.create_table("t", frame)
    t0 = time.perf_counter()
    d = _run_variants(c, frame)
    hit_rate = d["param_plan_hits"] / float(VARIANTS)
    print(f"on: compiles={d['compiles']} param_plan_hits="
          f"{d['param_plan_hits']} hit_rate={hit_rate:.2%} "
          f"({time.perf_counter() - t0:.1f}s)")
    if d["compiles"] > 2:
        return fail(f"{VARIANTS} variants of one shape paid "
                    f"{d['compiles']} compiles (want <= 2)")
    if hit_rate <= 0.90:
        return fail(f"plan-cache hit rate {hit_rate:.2%} (want > 90%)")
    if d["param_plans"] < VARIANTS:
        return fail(f"only {d['param_plans']}/{VARIANTS} plans were "
                    "parameterized")

    print("== kill switch (DSQL_PARAM_PLANS=0) ==")
    os.environ["DSQL_PARAM_PLANS"] = "0"
    try:
        c2 = Context()
        c2.create_table("t", frame)
        t0 = time.perf_counter()
        d0 = _run_variants(c2, frame)
        print(f"off: compiles={d0['compiles']} "
              f"({time.perf_counter() - t0:.1f}s)")
        if d0["compiles"] != VARIANTS:
            return fail(f"kill switch: expected {VARIANTS} value-baked "
                        f"compiles, got {d0['compiles']}")
        moved = {k: v for k, v in d0.items()
                 if k.startswith("param_") and v}
        if moved:
            return fail(f"kill switch: param counters moved: {moved}")
    finally:
        os.environ.pop("DSQL_PARAM_PLANS", None)

    print("== fresh process, never-seen literal, populated store ==")
    store_dir = tempfile.mkdtemp(prefix="param_smoke_store_")
    base_env = dict(os.environ,
                    JAX_PLATFORMS="cpu",
                    DSQL_PROGRAM_STORE=store_dir,
                    DSQL_RESULT_CACHE_MB="0",
                    DSQL_MAX_CONCURRENT_QUERIES="0",
                    DSQL_TIERED="0")
    base_env.pop("DSQL_FAULT_INJECT", None)
    populate = _run_phase(0.25, base_env)
    warm = _run_phase(0.75, base_env)   # DIFFERENT literal
    print(f"populate: compiles={populate['compiles']} "
          f"stores={populate['stores']}; "
          f"warm: compiles={warm['compiles']} hits={warm['hits']}")
    if populate["compiles"] < 1 or populate["stores"] < 1:
        return fail("populate process did not persist its program")
    if warm["compiles"] != 0:
        return fail(f"fresh process paid {warm['compiles']} compiles for a "
                    "new literal of a stored shape")
    if warm["hits"] < 1 or warm["param_plan_hits"] < 1:
        return fail("fresh process did not hit the store for the shape")
    # the stored program must be fed the NEW literal, not replay the old
    # one: the warm answer must equal the warm-literal pandas oracle
    for lit, got in ((0.25, populate["result"]), (0.75, warm["result"])):
        exp = _oracle(frame, lit)
        ok = (got["k"] == [int(x) for x in exp["k"]]
              and got["n"] == [int(x) for x in exp["n"]]
              and all(abs(a - float(b)) < 1e-4
                      for a, b in zip(got["s"], exp["s"])))
        if not ok:
            return fail(f"literal {lit}: fresh-process answer does not "
                        "match the pandas oracle (baked literal?)")
    if populate["result"] == warm["result"]:
        return fail("different literals returned identical results")

    print("param smoke OK")
    return 0


if __name__ == "__main__":
    if "--phase=child" in sys.argv[1:]:
        sys.exit(_phase_main("child"))
    sys.exit(main())
