#!/usr/bin/env python
"""Workload-manager smoke gate: saturation must queue, prioritize, and
disable cleanly.

Run by scripts/ci_local.sh (mirroring fault_smoke.py / obs_smoke.py /
cache_smoke.py):

    python scripts/sched_smoke.py

Asserts, against a real Context on generated data with a 2-slot scheduler:

  1. 8 concurrent mixed-priority queries (4 interactive + 4 batch) fired
     while both slots are held all complete — ZERO queries lost — and every
     one records a ``queued`` phase in its QueryReport;
  2. the interactive class's p50 queue time beats the batch class's p50
     (the deficit-weighted pick is actually prioritizing);
  3. admission telemetry reconciles: per-class admitted counters sum to
     exactly the queries submitted, with zero rejections/timeouts;
  4. ``DSQL_MAX_CONCURRENT_QUERIES=0`` restores exact pre-subsystem
     behavior: no queued span, no slot accounting, same answer.

Exit 0 on success — if the scheduler silently rots (slots leak, priorities
invert, the disable path stops bypassing), this gate fails loudly.
"""
import os
import statistics
import sys
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this gate asserts SYNCHRONOUS compile behavior; tiered execution
# (eager-first + background compile, on by default) is gated by
# scripts/warmstart_smoke.py instead
os.environ.setdefault("DSQL_TIERED", "0")
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "2"
os.environ["DSQL_QUEUE_DEPTH"] = "16"
os.environ["DSQL_QUEUE_TIMEOUT_MS"] = "120000"
# the result cache would serve repeats instantly and collapse the
# contention this smoke depends on
os.environ["DSQL_RESULT_CACHE_MB"] = "0"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import scheduler as sched  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

N_PER_CLASS = 4


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def main() -> int:
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({"a": list(range(5000))}))
    mgr = sched.get_manager()
    counters0 = {k: tel.REGISTRY.get(k)
                 for k in tel.STABLE_COUNTERS if k.startswith("sched_")}

    # -- 1+2. saturate 2 slots, fire 8 mixed-priority queries --------------
    # both slots are held by background tickets while the burst enqueues,
    # so EVERY burst query measures a real queue wait and the priority
    # pick (not arrival order) decides who runs first
    holders = [mgr.acquire("background", 0), mgr.acquire("background", 0)]
    results, queued_ms, lock = {}, {}, threading.Lock()

    def go(priority, i):
        # distinct literals -> distinct programs: each admitted query
        # holds its slot through a real compile
        out = ctx.sql(f"SELECT SUM(a + {i}) AS s FROM t",
                      return_futures=False, priority=priority)
        rep = tel.last_report()          # thread-local: race-free
        with lock:
            results[(priority, i)] = int(out["s"][0])
            queued_ms[(priority, i)] = rep.phases.get("queued")

    threads = []
    for i in range(N_PER_CLASS):
        threads.append(threading.Thread(target=go, args=("batch", i)))
    for i in range(N_PER_CLASS):
        threads.append(threading.Thread(
            target=go, args=("interactive", N_PER_CLASS + i)))
    for t in threads:
        t.start()
    # wait until all 8 are queued, then open the gates
    import time
    deadline = time.time() + 30
    while mgr.queue_depth() < 2 * N_PER_CLASS and time.time() < deadline:
        time.sleep(0.01)
    if mgr.queue_depth() < 2 * N_PER_CLASS:
        return fail(f"burst never fully queued ({mgr.queue_depth()}/8)")
    for h in holders:
        mgr.release(h)
    for t in threads:
        t.join(timeout=180)

    if len(results) != 2 * N_PER_CLASS:
        return fail(f"queries lost: {len(results)}/8 completed")
    base = sum(range(5000))
    for (_, i), got in results.items():
        if got != base + 5000 * i:
            return fail(f"wrong answer for query {i}: {got}")
    missing = [k for k, v in queued_ms.items() if v is None]
    if missing:
        return fail(f"no queued phase recorded for {missing}")
    p50_i = statistics.median(v for (p, _), v in queued_ms.items()
                              if p == "interactive")
    p50_b = statistics.median(v for (p, _), v in queued_ms.items()
                              if p == "batch")
    if p50_i >= p50_b:
        return fail(f"interactive p50 queue time ({p50_i:.1f} ms) not "
                    f"below batch p50 ({p50_b:.1f} ms)")
    print(f"ok priority: 8/8 completed; queue-time p50 "
          f"interactive={p50_i:.1f}ms < batch={p50_b:.1f}ms")

    # -- 3. telemetry reconciles -------------------------------------------
    deltas = {k: tel.REGISTRY.get(k) - counters0[k] for k in counters0}
    want = {"sched_admitted_interactive": N_PER_CLASS,
            "sched_admitted_batch": N_PER_CLASS,
            "sched_admitted_background": 2}      # the two slot holders
    for k, v in want.items():
        if deltas.get(k) != v:
            return fail(f"{k} delta {deltas.get(k)} != {v} ({deltas})")
    bad = {k: v for k, v in deltas.items()
           if ("rejected" in k or "timeout" in k) and v}
    if bad:
        return fail(f"unexpected rejections/timeouts: {bad}")
    if mgr.running_count() != 0 or mgr.queue_depth() != 0:
        return fail("slots leaked after the burst")
    print("ok telemetry: admitted counters reconcile (8 queries + 2 "
          "holders), zero rejected/timeout, zero leaked slots")

    # -- 4. full disable restores pre-subsystem behavior -------------------
    os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
    try:
        out = ctx.sql("SELECT SUM(a + 0) AS s FROM t", return_futures=False)
        rep = ctx.last_report
        if int(out["s"][0]) != base:
            return fail("disabled run returned a wrong answer")
        if "queued" in rep.phases or rep.span_count("queued"):
            return fail("disabled run still passed through admission")
        if mgr.enabled():
            return fail("manager claims enabled at "
                        "DSQL_MAX_CONCURRENT_QUERIES=0")
    finally:
        os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "2"
    print("ok disable: DSQL_MAX_CONCURRENT_QUERIES=0 bypasses the "
          "subsystem entirely")

    print("scheduler smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
