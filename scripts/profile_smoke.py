#!/usr/bin/env python
"""Device-profiler smoke gate: EXPLAIN PROFILE must measure real queries.

Run by scripts/ci_local.sh (mirroring scripts/obs_smoke.py):

    python scripts/profile_smoke.py

TPC-H queries run on the 8-virtual-device CPU mesh with the profiler
armed (``DSQL_PROFILE=1``); the gate asserts

  1. ``EXPLAIN PROFILE`` renders per-stage XLA cost (nonzero flops +
     bytes), one HBM row per device (8), and — on the join query — the
     collective-bytes line split by kind and a sane shard-skew ratio;
  2. the cost-model estimate rung closes: a repeat run with the stats
     rung off and a FRESH history file reserves from the captured XLA
     cost (envelope journals ``est_source="cost_model"``);
  3. ``system.devices`` answers through plain SQL with one row per
     device;
  4. ``GET /v1/engine`` carries the ``devices`` and ``profile``
     sections;
  5. the flight-recorder envelope carries the new skew / collective /
     cost-error fields;
  6. the disabled path is ZERO-cost: a child process with
     ``DSQL_PROFILE=0`` never imports the profiler module and
     ``EXPLAIN PROFILE`` prints the pointer line without executing.

Exit 0 on success.
"""
import json
import os
import re
import subprocess
import sys
import tempfile
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = \
        (os.environ.get("XLA_FLAGS", "") + " " + _FLAG).strip()
os.environ["DSQL_PROFILE"] = "1"
# synchronous compiles (the cost capture rides the compile) and no stats
# rung (it outranks the cost-model rung this gate must prove out)
os.environ.setdefault("DSQL_TIERED", "0")
os.environ["DSQL_ADAPTIVE"] = "0"
os.environ.pop("DSQL_HISTORY_FILE", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from benchmarks.tpch import QUERIES, generate_tpch  # noqa: E402
from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.parallel.mesh import default_mesh  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

SUBSET = (1, 3, 6)   # agg-heavy, join+agg+topk, scan/filter
SF = 0.002
N_DEV = 8


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _profile_lines(ctx, sql):
    out = ctx.sql("EXPLAIN PROFILE " + sql, return_futures=False)
    return [str(l) for l in out["PLAN"]]


def main() -> int:
    mesh = default_mesh()
    if int(mesh.devices.size) != N_DEV:
        return fail(f"expected {N_DEV}-device mesh, got {mesh.devices.size}")
    data = generate_tpch(SF)
    ctx = Context(mesh=mesh)
    for name, df in data.items():
        ctx.create_table(name, df)

    # -- 1. EXPLAIN PROFILE over the mesh ------------------------------------
    flops_re = re.compile(r"flops=([0-9.]+)")
    for qid in SUBSET:
        lines = _profile_lines(ctx, QUERIES[qid])
        stage_lines = [l for l in lines if l.startswith("-- stage")]
        if not stage_lines:
            return fail(f"q{qid}: no per-stage profile rows:\n"
                        + "\n".join(lines))
        flops = [float(m.group(1)) for l in stage_lines
                 for m in [flops_re.search(l)] if m]
        if not flops or sum(flops) <= 0:
            return fail(f"q{qid}: no nonzero flops in {stage_lines}")
        dev_rows = [l for l in lines if l.startswith("-- device")]
        if len(dev_rows) != N_DEV:
            return fail(f"q{qid}: {len(dev_rows)} device rows, "
                        f"want {N_DEV}")
        skews = [float(m.group(1)) for l in lines
                 for m in [re.search(r"skew_ratio: ([0-9.]+)", l)] if m]
        if any(s < 1.0 or s > N_DEV + 0.5 for s in skews):
            return fail(f"q{qid}: insane skew ratio {skews}")
        print(f"ok q{qid}: {len(stage_lines)} stage row(s) "
              f"flops={sum(flops):.0f} devices={len(dev_rows)} "
              f"skew={skews or 'n/a'}")
    q3_lines = _profile_lines(ctx, QUERIES[3])
    coll = [l for l in q3_lines if l.startswith("-- collectives")]
    if not coll or not re.search(r"(all_gather|all_to_all)=[1-9]", coll[0]):
        return fail(f"q3: no collective bytes by kind: {coll}")
    print(f"ok collectives: {coll[0][3:].strip()}")

    # -- 2. cost-model estimate rung -----------------------------------------
    solo = Context()
    solo.create_table("pt", {"a": list(range(2000)),
                             "b": [i % 11 for i in range(2000)]})
    q = "SELECT b, SUM(a) AS s FROM pt GROUP BY b"
    solo.sql(q, return_futures=False)   # run 1: cost ledger fills at compile
    before = tel.REGISTRY.get("estimate_from_cost_model")
    hist = os.path.join(tempfile.mkdtemp(prefix="dsql_prof_"),
                        "history.jsonl")
    os.environ["DSQL_HISTORY_FILE"] = hist  # fresh: history rung misses
    try:
        solo.sql(q, return_futures=False)
        if tel.REGISTRY.get("estimate_from_cost_model") <= before:
            return fail("estimate_from_cost_model did not advance")
        from dask_sql_tpu.runtime import flight_recorder as fr
        ev = fr.read_events(kind="query")[-1]
        if ev.get("est_source") != "cost_model":
            return fail(f"repeat run estimated from "
                        f"{ev.get('est_source')!r}, not cost_model")
        # -- 5. envelope carries the new fields -------------------------------
        for key in ("skew_ratio", "collective_bytes", "cost_err"):
            if key not in ev:
                return fail(f"envelope missing {key!r}: {sorted(ev)}")
        print(f"ok cost-model rung: est={ev['est_bytes']}B "
              f"cost_err={ev['cost_err']}")
    finally:
        del os.environ["DSQL_HISTORY_FILE"]

    # -- 3. system.devices through SQL ---------------------------------------
    dev = ctx.sql("SELECT device_id, platform, bytes_in_use "
                  "FROM system.devices", return_futures=False)
    if len(dev) != N_DEV:
        return fail(f"system.devices has {len(dev)} rows, want {N_DEV}")
    print(f"ok system.devices: {len(dev)} rows")

    # -- 4. /v1/engine sections ----------------------------------------------
    srv = ctx.run_server(host="127.0.0.1", port=0, blocking=False)
    try:
        base = f"http://127.0.0.1:{srv.server_port}"
        with urllib.request.urlopen(f"{base}/v1/engine") as r:
            snap = json.loads(r.read())
        if len(snap.get("devices", [])) != N_DEV:
            return fail(f"/v1/engine devices: {snap.get('devices')}")
        prof = snap.get("profile", {})
        if not prof.get("enabled") or prof.get("samples", 0) < 1:
            return fail(f"/v1/engine profile section dead: {prof}")
        print(f"ok /v1/engine: devices={len(snap['devices'])} "
              f"profile samples={prof['samples']}")
    finally:
        srv.shutdown()
        ctx.server = None

    # -- 6. disabled path is zero-cost ---------------------------------------
    child_code = (
        "import sys\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', {'a': [1, 2, 3, 4]})\n"
        "c.sql('SELECT SUM(a) AS s FROM t', return_futures=False)\n"
        "out = c.sql('EXPLAIN PROFILE SELECT SUM(a) AS s FROM t',\n"
        "            return_futures=False)\n"
        "lines = [str(l) for l in out['PLAN']]\n"
        "assert 'dask_sql_tpu.runtime.profiler' not in sys.modules, \\\n"
        "    'profiler imported with DSQL_PROFILE=0'\n"
        "assert any('profile: disabled' in l for l in lines), lines\n"
        "assert not any(l.startswith('-- stage') for l in lines), lines\n"
        "print('child ok')\n"
    )
    env = dict(os.environ)
    env["DSQL_PROFILE"] = "0"
    env.pop("XLA_FLAGS", None)   # single device is fine (and faster)
    proc = subprocess.run([sys.executable, "-c", child_code], env=env,
                          capture_output=True, timeout=600)
    if proc.returncode != 0 or b"child ok" not in proc.stdout:
        return fail(f"disabled-path child: {proc.stderr.decode()[-500:]}")
    print("ok disabled path: zero profiler imports, no execution")

    print("profile smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
