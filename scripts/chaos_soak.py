#!/usr/bin/env python
"""Chaos-soak gate: randomized faults under concurrent mixed-priority load.

The resilience suites prove each failure path in isolation; production
breaks at the COMPOSITION — a stage replay racing an admission rejection
racing a cache-populate fault.  This harness is the continuous rehearsal
the ROADMAP's serving story needs (run by scripts/ci_local.sh as
``python scripts/chaos_soak.py --budget-s 45``; the long variant rides the
``slow`` pytest marker in tests/integration/test_chaos_soak.py):

  * ``--clients`` concurrent client threads (default 4) submit random
    queries from a fixed menu (agg / join+agg / filter+topk / global agg /
    chunked streaming) at random priorities through the armed workload
    manager (2 slots) for ``--budget-s`` seconds — each tagged with a
    tenant identity (``t0``/``t1``) so the per-tenant accounting and the
    armed circuit breaker (``DSQL_TENANT_BREAKER``; the rare FATAL faults
    feed it) see real mixed traffic;
  * one HTTP client drives a live server with small
    ``DSQL_RESULT_PAGE_ROWS``: it submits a 2000-row query under the
    ``web`` tenant and either drains the whole ``nextUri`` page chain
    (oracle-checked) or DISCONNECTS mid-pagination, leaving the reaper
    (``DSQL_RESULT_TTL_S``) to GC the abandoned pages and futures;
  * one MV-churn client appends random batches into its own base table
    and reads a maintained materialized view against a self-maintained
    pandas oracle — the ``mv_refresh`` site makes incremental refreshes
    fall back to full recomputes mid-soak (wrong-never, slower-ok);
  * EVERY injection site (runtime/faults.py SITES) is armed
    probabilistically at ``--p`` (default 0.05) with per-site seeds, plus
    a rarer FATAL compile fault that exercises the exile + quarantine
    paths (a temp ``DSQL_QUARANTINE_FILE`` is armed);
  * every successful result is checked against a precomputed pandas
    oracle.

Engine-wide invariants asserted at the end — the acceptance bar:

  1. ZERO wrong results (a fault may slow or fail a query, never corrupt
     one);
  2. ZERO lost/hung queries: every submission reaches a terminal outcome
     (result or typed ResilienceError) and every client thread joins;
  3. ZERO untyped failures escaping the engine;
  4. counters reconcile: admitted + rejected + timeout + injected
     admission faults + tenant quota/circuit rejects == submissions
     (ctx AND wire clients), per-tenant submitted == admitted + rejects
     with zero inflight grants, and the scheduler ends with no running
     slots or queue ghosts;
  5. nothing leaks: the reaper clears every abandoned spool/future/seat
     and the spill store ends with zero runs;
  6. the engine is healthy AFTER the soak: with faults disarmed, every
     menu query answers oracle-correct.

Exit 0 on success.
"""
import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this gate asserts SYNCHRONOUS compile behavior; tiered execution
# (eager-first + background compile, on by default) is gated by
# scripts/warmstart_smoke.py instead
os.environ.setdefault("DSQL_TIERED", "0")
os.environ.setdefault("DSQL_MAX_CONCURRENT_QUERIES", "2")
os.environ.setdefault("DSQL_QUEUE_DEPTH", "64")
os.environ.setdefault("DSQL_QUEUE_TIMEOUT_MS", "120000")
os.environ.setdefault("DSQL_RETRY_BASE_MS", "1")
# out-of-core on: the two-chunked join menu entry must route through the
# grace-hash spill path so the ``spill`` + ``chunked_read`` fault sites
# see real traffic (spill dir is per-run temp, cleaned by the OS)
os.environ.setdefault("DSQL_SPILL_MB", "64")
os.environ.setdefault("DSQL_SPILL_DIR",
                      tempfile.mkdtemp(prefix="dsql_chaos_spill_"))
# stage every multi-heavy plan so the stage-exec/stage-replay failure
# domain is actually in play on the small soak queries
os.environ.setdefault("DSQL_STAGE_HEAVY", "1")
# small pages + a short TTL put the result spooler and its reaper in the
# blast radius: the HTTP client pages 2000-row results 200 rows at a
# time and ABANDONS half of them mid-chain for the reaper to GC
os.environ.setdefault("DSQL_RESULT_PAGE_ROWS", "200")
os.environ.setdefault("DSQL_RESULT_TTL_S", "3")
# WAL-armed ingest (ISSUE 20): every append in the soak routes through
# the write-ahead log + the ``ingest`` fault site, and two dedicated
# clients keep join/DISTINCT views oracle-exact against acked batches
os.environ.setdefault("DSQL_INGEST_DIR",
                      tempfile.mkdtemp(prefix="dsql_chaos_ingest_"))
# arm the per-tenant circuit breaker so the rare FATAL compile faults
# exercise trip -> open -> half-open probe -> close IN-SOAK
os.environ.setdefault("DSQL_TENANT_BREAKER", "3")
os.environ.setdefault("DSQL_TENANT_BREAKER_TTL_S", "2")
os.environ.setdefault("DSQL_TENANT_BREAKER_PROBE_S", "1")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

N_ROWS = 2000
PRIORITIES = ("interactive", "batch", "background")
QUERY_TIMEOUT_S = 30.0
JOIN_GRACE_S = 90.0


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64").round(6)
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def _make_data(seed: int):
    rng = np.random.default_rng(seed)
    t1 = pd.DataFrame({
        "k": rng.integers(0, 20, N_ROWS),
        "v": np.round(rng.random(N_ROWS) * 10, 3),
        "w": rng.integers(0, 100, N_ROWS),
    })
    t2 = pd.DataFrame({
        "k": rng.integers(0, 20, N_ROWS // 2),
        "c": np.round(rng.random(N_ROWS // 2) * 5, 3),
    })
    return t1, t2


def _menu(t1: pd.DataFrame, t2: pd.DataFrame):
    """[(sql, pandas-oracle DataFrame)]: fixed queries, oracles computed
    once up front so the soak loop never consults the engine under test.
    Literal VARIANTS give distinct plan fingerprints, so the soak keeps
    compiling and executing fresh programs instead of collapsing into
    result-cache hits (which stay in the mix too — repeats are real
    traffic)."""
    j = t1.merge(t2, on="k")
    menu = [
        ("SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t1 GROUP BY k",
         t1.groupby("k", as_index=False).agg(s=("v", "sum"),
                                             n=("v", "size"))),
        ("SELECT t1.k AS k, SUM(t2.c) AS s FROM t1 "
         "JOIN t2 ON t1.k = t2.k GROUP BY t1.k",
         j.groupby("k", as_index=False).agg(s=("c", "sum"))),
        ("SELECT SUM(v) AS s, MIN(w) AS mn, MAX(w) AS mx FROM t1",
         pd.DataFrame({"s": [t1.v.sum()], "mn": [t1.w.min()],
                       "mx": [t1.w.max()]})),
        ("SELECT k, SUM(v) AS s FROM tc GROUP BY k",
         t1.groupby("k", as_index=False).agg(s=("v", "sum"))),
        # two chunked sides: grace-hash partitioned join through the spill
        # store (arms the ``spill`` site; partitions stream back per pair)
        ("SELECT tc.k AS k, SUM(tc2.c) AS s FROM tc "
         "JOIN tc2 ON tc.k = tc2.k GROUP BY tc.k",
         j.groupby("k", as_index=False).agg(s=("c", "sum"))),
    ]
    for x in (2, 4, 6, 8):
        sql = (f"SELECT k, v FROM t1 WHERE v > {x}.0 "
               "ORDER BY v DESC, k LIMIT 50")
        menu.append((sql, t1[t1.v > float(x)]
                     .sort_values(["v", "k"], ascending=[False, True])
                     [["k", "v"]].head(50)))
        sql = (f"SELECT t1.k AS k, SUM(t2.c) AS s FROM t1 "
               f"JOIN t2 ON t1.k = t2.k WHERE t1.w < {x * 12} "
               "GROUP BY t1.k")
        jw = j[j.w < x * 12]
        menu.append((sql, jw.groupby("k", as_index=False)
                     .agg(s=("c", "sum"))))
    return menu


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget-s", type=float, default=45.0)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--p", type=float, default=0.05)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    qdir = tempfile.mkdtemp(prefix="dsql_chaos_")
    os.environ["DSQL_QUARANTINE_FILE"] = os.path.join(qdir, "quarantine.json")
    os.environ["DSQL_QUARANTINE_TTL_S"] = "5"      # let probes happen in-soak
    # autopilot armed for the whole soak: the advisor ticks under the same
    # fault stream as the clients (the ``autopilot`` site degrades a tick
    # to a journaled no-op), auto-materializes whatever the mixed workload
    # makes hot, and re-plans skewed joins — all while every client below
    # keeps asserting pandas-oracle answers
    os.environ["DSQL_HISTORY_FILE"] = os.path.join(qdir, "history.jsonl")
    os.environ["DSQL_AUTOPILOT"] = "1"
    os.environ["DSQL_AUTOPILOT_INTERVAL_S"] = "0"  # the client ticks
    os.environ["DSQL_AUTOPILOT_MIN_HITS"] = "3"

    from dask_sql_tpu import Context
    from dask_sql_tpu.runtime import autopilot as autopilot_mod
    from dask_sql_tpu.runtime import faults
    from dask_sql_tpu.runtime import resilience as res
    from dask_sql_tpu.runtime import scheduler as sched
    from dask_sql_tpu.runtime import telemetry as tel
    from dask_sql_tpu.runtime import tenancy
    from dask_sql_tpu.server.app import run_server

    t1, t2 = _make_data(args.seed)
    ctx = Context()
    ctx.create_table("t1", t1)
    ctx.create_table("t2", t2)
    # chunked registration exercises the streaming sites; the second
    # chunked table forces the grace-hash join (spill sites) in the menu
    ctx.create_table("tc", t1, chunked=True, batch_rows=512)
    ctx.create_table("tc2", t2, chunked=True, batch_rows=512)
    menu = _menu(t1, t2)

    # the MV-churn client's private base + maintained view (built before
    # faults arm: the soak measures the loop, not the setup)
    tm = t1[["k", "v"]].copy()
    ctx.create_table("tm", tm)
    ctx.sql("CREATE MATERIALIZED VIEW vm AS "
            "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM tm GROUP BY k")

    # the autopilot client's private base: ITS aggregate goes hot so the
    # advisor auto-materializes it mid-soak, and appends force O(delta)
    # refreshes on the serve path
    ta = t1[["k", "v"]].copy()
    ctx.create_table("ta", ta)

    # the ingest clients' private bases + maintained join/DISTINCT views
    # (ISSUE 20): one writer appends over the wire (POST /v1/ingest), one
    # in-process; a faulted append is rejected BEFORE the WAL commit point
    # (never half-committed), so each oracle advances only on acked batches
    rngj = np.random.RandomState(args.seed + 5)
    tij = pd.DataFrame({"k": rngj.randint(0, 20, 500),
                        "v": np.round(rngj.rand(500) * 10, 3)})
    tdj = pd.DataFrame({"k": np.arange(20),
                        "c": np.round(np.arange(20) * 0.5, 3)})
    ctx.create_table("tij", tij)
    ctx.create_table("tdj", tdj)
    ctx.sql("CREATE MATERIALIZED VIEW vji AS "
            "SELECT tij.k AS k, tij.v AS v, tdj.c AS c "
            "FROM tij JOIN tdj ON tij.k = tdj.k")
    tdc = pd.DataFrame({"k": rngj.randint(0, 50, 400)})
    ctx.create_table("tdc", tdc)
    ctx.sql("CREATE MATERIALIZED VIEW vdc AS "
            "SELECT COUNT(DISTINCT k) AS n FROM tdc")

    # probabilistic faults on EVERY site, deterministic per-site streams,
    # plus a rare FATAL compile fault (exile + quarantine coverage)
    spec = ",".join(f"{s}:p={args.p}:seed={args.seed + i}"
                    for i, s in enumerate(faults.SITES))
    spec += f",compile:p={args.p / 5:.4f}:seed={args.seed + 100}:fatal"
    os.environ["DSQL_FAULT_INJECT"] = spec

    # the wire client's server shares ctx, scheduler and spill store with
    # the in-process clients — the composition under test
    srv = run_server(context=ctx, host="127.0.0.1", port=0, blocking=False)
    base = f"http://127.0.0.1:{srv.server_port}"

    c0 = tel.REGISTRY.counters()
    lock = threading.Lock()
    stats = {"submitted": 0, "ok": 0, "typed": 0, "untyped": 0, "wrong": 0}
    http = {"submitted": 0, "ok": 0, "typed": 0, "abandoned": 0,
            "untyped": 0, "wrong": 0}
    ing = {"appends": 0, "committed": 0, "rejected": 0, "untyped": 0}
    ing_state = {}  # final per-client oracles for the post-soak audit
    problems = []

    t_end = time.monotonic() + args.budget_s

    def client(tid: int) -> None:
        rng = random.Random(args.seed * 1000 + tid)
        while time.monotonic() < t_end:
            sql, oracle = menu[rng.randrange(len(menu))]
            pr = PRIORITIES[rng.randrange(len(PRIORITIES))]
            with lock:
                stats["submitted"] += 1
            try:
                got = ctx.sql(sql, return_futures=False,
                              timeout=QUERY_TIMEOUT_S, priority=pr,
                              tenant=f"t{tid % 2}")
            except res.ResilienceError:
                with lock:
                    stats["typed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    stats["untyped"] += 1
                    problems.append(f"untyped {type(e).__name__} on "
                                    f"{sql!r}: {e}")
                continue
            try:
                pd.testing.assert_frame_equal(
                    _norm(got), _norm(oracle), check_dtype=False,
                    rtol=1e-6, atol=1e-9)
            except AssertionError as e:
                with lock:
                    stats["wrong"] += 1
                    problems.append(f"WRONG RESULT on {sql!r}: "
                                    f"{str(e)[:300]}")
                continue
            with lock:
                stats["ok"] += 1

    def mv_client() -> None:
        # single mutator of tm: the pandas oracle below is authoritative.
        # Appends go through Context.append_rows directly (deterministic —
        # under the armed WAL the mutation either commits with its delta
        # record or raises a typed error BEFORE the commit point), reads
        # go through the full ctx.sql path where admission faults, refresh
        # faults, and the scheduler apply.
        rng = random.Random(args.seed * 1000 + 7777)
        oracle = tm.copy()
        while time.monotonic() < t_end:
            if rng.random() < 0.4:
                add = pd.DataFrame({
                    "k": [rng.randrange(20) for _ in range(8)],
                    "v": [round(rng.random() * 10, 3) for _ in range(8)],
                })
                try:
                    ctx.append_rows("tm", add)
                except res.ResilienceError:
                    continue  # rejected pre-commit: oracle unchanged
                oracle = pd.concat([oracle, add], ignore_index=True)
                continue
            expected = oracle.groupby("k", as_index=False).agg(
                s=("v", "sum"), n=("v", "size"))
            pr = PRIORITIES[rng.randrange(len(PRIORITIES))]
            with lock:
                stats["submitted"] += 1
            try:
                got = ctx.sql("SELECT * FROM vm", return_futures=False,
                              timeout=QUERY_TIMEOUT_S, priority=pr)
            except res.ResilienceError:
                with lock:
                    stats["typed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    stats["untyped"] += 1
                    problems.append(f"untyped {type(e).__name__} on the "
                                    f"matview read: {e}")
                continue
            try:
                pd.testing.assert_frame_equal(
                    _norm(got), _norm(expected), check_dtype=False,
                    rtol=1e-6, atol=1e-9)
            except AssertionError as e:
                with lock:
                    stats["wrong"] += 1
                    problems.append("WRONG RESULT on the matview read "
                                    f"(stale or corrupt): {str(e)[:300]}")
                continue
            with lock:
                stats["ok"] += 1

    def autopilot_client() -> None:
        # repeats ONE aggregate shape so the advisor sees a hot candidate,
        # appends occasionally so serves must refresh O(delta), and ticks
        # the advisor explicitly under the same fault stream as everything
        # else — the loop may stall (tick_fault), never corrupt an answer
        rng = random.Random(args.seed * 1000 + 8888)
        oracle = ta.copy()
        sql = "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM ta GROUP BY k"
        while time.monotonic() < t_end:
            autopilot_mod.tick(ctx)
            if rng.random() < 0.3:
                add = pd.DataFrame({
                    "k": [rng.randrange(20) for _ in range(8)],
                    "v": [round(rng.random() * 10, 3) for _ in range(8)],
                })
                try:
                    ctx.append_rows("ta", add)
                except res.ResilienceError:
                    continue  # rejected pre-commit: oracle unchanged
                oracle = pd.concat([oracle, add], ignore_index=True)
                continue
            expected = oracle.groupby("k", as_index=False).agg(
                s=("v", "sum"), n=("v", "size"))
            pr = PRIORITIES[rng.randrange(len(PRIORITIES))]
            with lock:
                stats["submitted"] += 1
            try:
                got = ctx.sql(sql, return_futures=False,
                              timeout=QUERY_TIMEOUT_S, priority=pr)
            except res.ResilienceError:
                with lock:
                    stats["typed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    stats["untyped"] += 1
                    problems.append(f"untyped {type(e).__name__} on the "
                                    f"autopilot-managed read: {e}")
                continue
            try:
                pd.testing.assert_frame_equal(
                    _norm(got), _norm(expected), check_dtype=False,
                    rtol=1e-6, atol=1e-9)
            except AssertionError as e:
                with lock:
                    stats["wrong"] += 1
                    problems.append("WRONG RESULT on the autopilot-managed "
                                    f"read (stale serve?): {str(e)[:300]}")
                continue
            with lock:
                stats["ok"] += 1

    def ingest_join_client() -> None:
        # the WAL-armed dashboard pair, wire flavor: appends go through
        # POST /v1/ingest (tenant-tagged, quota-governed), reads serve the
        # maintained delta-join view.  The oracle advances only on an
        # HTTP 200 COMMITTED ack; a faulted/backpressured append is a
        # typed rejection with nothing durable behind it.
        rng = random.Random(args.seed * 1000 + 9999)
        oracle = tij.copy()

        def post(rows):
            req = urllib.request.Request(
                f"{base}/v1/ingest",
                data=json.dumps({"table": "tij", "rows": rows}).encode(),
                method="POST", headers={"X-DSQL-Tenant": "web"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        while time.monotonic() < t_end:
            if rng.random() < 0.4:
                rows = [[rng.randrange(20), round(rng.random() * 10, 3)]
                        for _ in range(6)]
                with lock:
                    ing["appends"] += 1
                try:
                    resp = post(rows)
                except urllib.error.HTTPError as e:
                    try:
                        err = json.loads(e.read()).get("error", {})
                    except Exception:  # noqa: BLE001
                        err = {}
                    with lock:
                        if err.get("errorName"):
                            ing["rejected"] += 1
                        else:
                            ing["untyped"] += 1
                            problems.append("untyped ingest wire failure: "
                                            f"HTTP {e.code} without an "
                                            "errorName")
                    if e.code == 429:
                        time.sleep(0.2)
                    continue
                except Exception as e:  # noqa: BLE001 - the gate records it
                    with lock:
                        ing["untyped"] += 1
                        problems.append("untyped ingest-writer failure: "
                                        f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    if resp.get("state") != "COMMITTED":
                        ing["untyped"] += 1
                        problems.append(f"unexpected ingest ack: {resp}")
                        continue
                    ing["committed"] += 1
                oracle = pd.concat(
                    [oracle, pd.DataFrame(rows, columns=["k", "v"])],
                    ignore_index=True)
                continue
            expected = oracle.merge(tdj, on="k")[["k", "v", "c"]]
            pr = PRIORITIES[rng.randrange(len(PRIORITIES))]
            with lock:
                stats["submitted"] += 1
            try:
                got = ctx.sql("SELECT * FROM vji", return_futures=False,
                              timeout=QUERY_TIMEOUT_S, priority=pr)
            except res.ResilienceError:
                with lock:
                    stats["typed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    stats["untyped"] += 1
                    problems.append(f"untyped {type(e).__name__} on the "
                                    f"delta-join view read: {e}")
                continue
            try:
                pd.testing.assert_frame_equal(
                    _norm(got), _norm(expected), check_dtype=False,
                    rtol=1e-6, atol=1e-9)
            except AssertionError as e:
                with lock:
                    stats["wrong"] += 1
                    problems.append("WRONG RESULT on the delta-join view "
                                    f"(stale or corrupt): {str(e)[:300]}")
                continue
            with lock:
                stats["ok"] += 1
        ing_state["tij"] = oracle

    def ingest_distinct_client() -> None:
        # in-process flavor over a COUNT(DISTINCT) view (refcounted value
        # state): single mutator of tdc, so the nunique oracle is exact
        rng = random.Random(args.seed * 1000 + 6666)
        oracle = tdc.copy()
        while time.monotonic() < t_end:
            if rng.random() < 0.4:
                add = pd.DataFrame(
                    {"k": [rng.randrange(50) for _ in range(5)]})
                with lock:
                    ing["appends"] += 1
                try:
                    ctx.append_rows("tdc", add)
                except res.ResilienceError:
                    with lock:
                        ing["rejected"] += 1
                    continue
                except Exception as e:  # noqa: BLE001 - the gate records it
                    with lock:
                        ing["untyped"] += 1
                        problems.append("untyped ingest append failure: "
                                        f"{type(e).__name__}: {e}")
                    continue
                with lock:
                    ing["committed"] += 1
                oracle = pd.concat([oracle, add], ignore_index=True)
                continue
            expected_n = int(oracle["k"].nunique())
            pr = PRIORITIES[rng.randrange(len(PRIORITIES))]
            with lock:
                stats["submitted"] += 1
            try:
                got = ctx.sql("SELECT n FROM vdc", return_futures=False,
                              timeout=QUERY_TIMEOUT_S, priority=pr)
            except res.ResilienceError:
                with lock:
                    stats["typed"] += 1
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    stats["untyped"] += 1
                    problems.append(f"untyped {type(e).__name__} on the "
                                    f"COUNT(DISTINCT) view read: {e}")
                continue
            if int(got["n"][0]) != expected_n:
                with lock:
                    stats["wrong"] += 1
                    problems.append("WRONG RESULT on the COUNT(DISTINCT) "
                                    f"view: {int(got['n'][0])} != "
                                    f"{expected_n}")
                continue
            with lock:
                stats["ok"] += 1
        ing_state["tdc"] = oracle

    def paging_client() -> None:
        # the wire-level tenant: pages 2000-row results through the spool
        # and walks away from half of them mid-chain (disconnect), leaving
        # the reaper to prove the no-leak invariant
        rng = random.Random(args.seed * 1000 + 8888)
        sql = "SELECT k, v FROM t1"
        oracle = t1[["k", "v"]]

        def fetch(url, body=None):
            req = urllib.request.Request(
                url, data=body, method="POST" if body else "GET",
                headers={"X-DSQL-Tenant": "web"} if body else {})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        while time.monotonic() < t_end:
            with lock:
                http["submitted"] += 1
            bail_after = rng.randrange(1, 8) if rng.random() < 0.5 else None
            try:
                payload = fetch(f"{base}/v1/statement", sql.encode())
                deadline = time.monotonic() + QUERY_TIMEOUT_S + 60
                rows, pages, failed, abandoned = [], 0, False, False
                while True:
                    if payload.get("stats", {}).get("state") == "FAILED":
                        failed = True
                        break
                    if payload.get("data"):
                        rows.extend(payload["data"])
                        pages += 1
                    uri = payload.get("nextUri")
                    if uri is None:
                        break
                    if ("/v1/result/" in uri and bail_after is not None
                            and pages >= bail_after):
                        abandoned = True   # hang up with pages spooled
                        break
                    if time.monotonic() > deadline:
                        raise TimeoutError("paging poll hung")
                    payload = fetch(uri)
            except urllib.error.HTTPError as e:
                # typed iff the wire payload carries an audited errorName
                # (429 quota/shed, 5xx fault verdicts); anything else is
                # an escape
                try:
                    err = json.loads(e.read()).get("error", {})
                except Exception:  # noqa: BLE001
                    err = {}
                with lock:
                    if err.get("errorName"):
                        http["typed"] += 1
                    else:
                        http["untyped"] += 1
                        problems.append("untyped wire failure: HTTP "
                                        f"{e.code} without an errorName")
                if e.code == 429:
                    time.sleep(0.2)
                continue
            except Exception as e:  # noqa: BLE001 - the gate records it
                with lock:
                    http["untyped"] += 1
                    problems.append("untyped paging-client failure: "
                                    f"{type(e).__name__}: {e}")
                continue
            if failed:
                with lock:
                    http["typed"] += 1
                continue
            if abandoned:
                with lock:
                    http["abandoned"] += 1
                continue
            try:
                got = pd.DataFrame(rows, columns=["k", "v"])
                pd.testing.assert_frame_equal(
                    _norm(got), _norm(oracle), check_dtype=False,
                    rtol=1e-6, atol=1e-9)
            except AssertionError as e:
                with lock:
                    http["wrong"] += 1
                    problems.append("WRONG RESULT over the paged wire: "
                                    f"{str(e)[:300]}")
                continue
            with lock:
                http["ok"] += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(args.clients)]
    threads.append(threading.Thread(target=mv_client, daemon=True))
    threads.append(threading.Thread(target=autopilot_client, daemon=True))
    threads.append(threading.Thread(target=ingest_join_client, daemon=True))
    threads.append(threading.Thread(target=ingest_distinct_client,
                                    daemon=True))
    threads.append(threading.Thread(target=paging_client, daemon=True))
    for th in threads:
        th.start()
    hung = 0
    join_by = time.monotonic() + args.budget_s + JOIN_GRACE_S
    for th in threads:
        th.join(timeout=max(join_by - time.monotonic(), 0.1))
        if th.is_alive():
            hung += 1

    # the reaper must clear every abandoned pagination (spool + future +
    # seat) on its own within DSQL_RESULT_TTL_S; only then stop the server
    state = srv.app_state
    reap_by = time.monotonic() + 20
    while time.monotonic() < reap_by and (
            state.spools or state.future_list or state.seats):
        time.sleep(0.1)
    if state.spools or state.future_list or state.seats:
        problems.append(
            "reaper leaked server state past the TTL: "
            f"spools={len(state.spools)} futures={len(state.future_list)} "
            f"seats={len(state.seats)} "
            f"(abandoned paginations: {http['abandoned']})")
    srv.shutdown()

    c1 = tel.REGISTRY.counters()

    def d(name: str) -> int:
        return c1.get(name, 0) - c0.get(name, 0)

    failures = list(problems)
    if hung:
        failures.append(f"{hung} client thread(s) hung past the "
                        f"{JOIN_GRACE_S:.0f} s grace — lost queries")
    if stats["wrong"]:
        failures.append(f"{stats['wrong']} wrong result(s)")
    if stats["untyped"]:
        failures.append(f"{stats['untyped']} untyped failure(s) escaped "
                        "the taxonomy")
    if stats["ok"] + stats["typed"] + stats["untyped"] + stats["wrong"] \
            != stats["submitted"]:
        failures.append("outcome counts do not sum to submissions")
    if stats["ok"] == 0:
        failures.append("no query succeeded — the soak proved nothing")
    if http["wrong"]:
        failures.append(f"{http['wrong']} wrong result(s) over the paged "
                        "wire")
    if http["untyped"]:
        failures.append(f"{http['untyped']} untyped wire failure(s)")
    if sum(http[k] for k in ("ok", "typed", "abandoned", "untyped",
                             "wrong")) != http["submitted"]:
        failures.append("wire outcome counts do not sum to submissions")
    if http["ok"] == 0:
        failures.append("no paged query fully drained — the spooler was "
                        "never proven under chaos")
    if http["abandoned"] == 0:
        failures.append("no pagination was abandoned — the reaper was "
                        "never exercised")
    if ing["untyped"]:
        failures.append(f"{ing['untyped']} untyped ingest failure(s)")
    if ing["committed"] == 0:
        failures.append("no ingest batch committed — the WAL writer was "
                        "never exercised")

    # scheduler reconciliation: every submission enters admission exactly
    # once and leaves as admitted | rejected | timeout | injected fault
    mgr = sched.get_manager()
    admitted = sum(d(f"sched_admitted_{p}") for p in PRIORITIES)
    rejected = sum(d(f"sched_rejected_{p}") for p in PRIORITIES)
    timeout = sum(d(f"sched_timeout_{p}") for p in PRIORITIES)
    adm_faults = d("fault_admission")
    # tenant rejects fire BEFORE the scheduler sees the query, so they
    # join the equation on the left
    ten_rejects = d("tenant_quota_rejects") + d("tenant_circuit_rejects")
    accounted = admitted + rejected + timeout + adm_faults + ten_rejects
    submitted_all = stats["submitted"] + http["submitted"]
    if accounted != submitted_all:
        failures.append(
            f"admission counters do not reconcile: admitted {admitted} + "
            f"rejected {rejected} + timeout {timeout} + injected "
            f"{adm_faults} + tenant rejects {ten_rejects} = {accounted} "
            f"!= submitted {submitted_all}")
    # per-tenant books must balance too, with no grant left inflight
    for row in tenancy.tenant_rows():
        if row["inflight"]:
            failures.append(f"tenant {row['tenant']!r} leaked "
                            f"{row['inflight']} inflight grant(s)")
        if row["submitted"] != (row["admitted"] + row["quota_rejects"]
                                + row["circuit_rejects"]):
            failures.append(f"tenant {row['tenant']!r} admission counters "
                            f"do not reconcile: {row}")
    if mgr.running_count() != 0 or mgr.queue_depth() != 0:
        failures.append(
            f"scheduler leaked state: running={mgr.running_count()} "
            f"queued={mgr.queue_depth()} after the soak")

    # post-soak health: faults disarmed, every menu query oracle-correct.
    # The per-tenant books were audited above; a breaker legitimately open
    # at soak end must not fail the health probes, so clear the registry.
    os.environ.pop("DSQL_FAULT_INJECT", None)
    faults.reset()
    tenancy.get_registry()._reset_for_tests()
    for sql, oracle in menu:
        try:
            got = ctx.sql(sql, return_futures=False, timeout=QUERY_TIMEOUT_S)
            pd.testing.assert_frame_equal(
                _norm(got), _norm(oracle), check_dtype=False,
                rtol=1e-6, atol=1e-9)
        except Exception as e:  # noqa: BLE001 - the gate records it
            failures.append(f"post-soak health check failed on {sql!r}: "
                            f"{type(e).__name__}: {str(e)[:200]}")

    # the maintained ingest views must end EXACTLY at the acked prefix:
    # every committed batch visible, every rejected one absent
    try:
        if "tij" in ing_state:
            want = ing_state["tij"].merge(tdj, on="k")[["k", "v", "c"]]
            got = ctx.sql("SELECT * FROM vji", return_futures=False,
                          timeout=QUERY_TIMEOUT_S)
            pd.testing.assert_frame_equal(_norm(got), _norm(want),
                                          check_dtype=False, rtol=1e-6,
                                          atol=1e-9)
        if "tdc" in ing_state:
            got = ctx.sql("SELECT n FROM vdc", return_futures=False,
                          timeout=QUERY_TIMEOUT_S)
            if int(got["n"][0]) != int(ing_state["tdc"]["k"].nunique()):
                raise AssertionError("COUNT(DISTINCT) drifted from the "
                                     "acked oracle")
    except Exception as e:  # noqa: BLE001 - the gate records it
        failures.append("post-soak ingest-view audit failed: "
                        f"{type(e).__name__}: {str(e)[:300]}")

    # spill hygiene: every grace run is freed on success AND error paths —
    # a surviving run after all clients joined is a leak
    from dask_sql_tpu.runtime import spill as spill_mod
    sstats = spill_mod.get_store().stats()
    if sstats["runs"]:
        failures.append(f"spill store leaked {sstats['runs']} run(s) "
                        "after the soak")

    interesting = ("retries", "degradations", "stage_replays",
                   "stage_replay_saved_stages", "stage_execs",
                   "quarantine_skips", "quarantine_probes",
                   "quarantine_marks", "exiled", "deadline_exceeded",
                   "result_cache_hits", "mv_serves",
                   "mv_refresh_incremental", "mv_refresh_full",
                   "mv_deltas_recorded", "autopilot_ticks",
                   "autopilot_mv_creates", "autopilot_mv_drops",
                   "autopilot_mv_serves", "autopilot_hints_recorded",
                   "autopilot_hints_applied", "autopilot_hints_reverted")
    if d("autopilot_ticks") == 0 and d("fault_autopilot") == 0:
        failures.append("the autopilot was never ticked — the advisor "
                        "was not exercised by the soak")
    fault_counts = {k: d(k) for k in c1 if k.startswith("fault_") and d(k)}
    print(f"chaos soak: {stats['submitted']} submitted over "
          f"{args.budget_s:.0f} s x {args.clients} clients (p={args.p}) -> "
          f"{stats['ok']} ok, {stats['typed']} typed failures, "
          f"{stats['wrong']} wrong, {stats['untyped']} untyped, "
          f"{hung} hung")
    print(f"  paged wire: {http['submitted']} submitted -> {http['ok']} "
          f"drained, {http['abandoned']} abandoned mid-page, "
          f"{http['typed']} typed, {http['wrong']} wrong, "
          f"{http['untyped']} untyped; "
          f"{d('result_pages_served')} pages served, "
          f"{d('result_reaped')} reaped")
    print(f"  ingest: {ing['appends']} appends -> {ing['committed']} "
          f"committed, {ing['rejected']} rejected (typed), "
          f"{ing['untyped']} untyped; "
          f"wal_bytes={int(tel.REGISTRY.gauges().get('ingest_wal_bytes', 0))}")
    print("  admission: "
          f"admitted={admitted} rejected={rejected} timeout={timeout} "
          f"injected={adm_faults} tenant_rejects={ten_rejects} "
          f"(circuit opens={d('tenant_circuit_opens')} "
          f"probes={d('tenant_circuit_probes')})")
    print("  faults fired: " + (", ".join(
        f"{k[len('fault_'):]}={v}" for k, v in sorted(fault_counts.items()))
        or "none"))
    print("  recovery: " + ", ".join(
        f"{k}={d(k)}" for k in interesting if d(k)))

    if failures:
        print("CHAOS SOAK FAILED:")
        for f in failures:
            print("  - " + f)
        return 1
    print("chaos soak OK: zero wrong results, zero lost queries, "
          "counters reconcile")
    return 0


if __name__ == "__main__":
    sys.exit(main())
