#!/usr/bin/env python
"""Multi-chip SPMD smoke gate (parallel/spmd.py).

Run by scripts/ci_local.sh (mirroring cache_smoke.py / stats_smoke.py):

    python scripts/shard_smoke.py

Asserts, on the 8-virtual-device CPU mesh against generated TPC-H data:

  1. **Q1 and Q6 run sharded and agree with the single-device engine**:
     the ``spmd_queries`` counter must advance (a silent fallback to the
     single-device path would still be correct — counters are the honest
     signal) with zero ``spmd_fallbacks``;
  2. **Q3 moves rows**: the 3-table join + group-by must fire exchange
     and/or broadcast join collectives, with ``spmd_exchange_bytes``
     accounting for the traffic;
  3. a **forced hash-partition exchange** (DSQL_SPMD_BROADCAST_ROWS=0)
     still produces the single-device answer;
  4. **DSQL_MESH=0 restores the baseline**: same answers, no spmd
     counters moving.

Exit 0 on success — if the sharded lowering drifts from the single-device
semantics, or the kill switch stops killing, this gate fails loudly.
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["DSQL_TIERED"] = "0"
os.environ["DSQL_RESULT_CACHE_MB"] = "0"
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
os.environ.pop("DSQL_MESH", None)
os.environ.pop("DSQL_SPMD_BROADCAST_ROWS", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from benchmarks.tpch import QUERIES, generate_tpch  # noqa: E402
from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.parallel.mesh import default_mesh  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402


def spmd_counters():
    snap = tel.REGISTRY.counters()
    return {k: int(v) for k, v in snap.items() if k.startswith("spmd_")}


def check_frames(qid, got, want, note=""):
    assert len(got) == len(want), \
        f"Q{qid}{note}: {len(got)} vs {len(want)} rows"
    for gc, wc in zip(got.columns, want.columns):
        g, w = got[gc].to_numpy(), want[wc].to_numpy()
        if g.dtype.kind == "f":
            assert np.allclose(g.astype(float), w.astype(float),
                               rtol=1e-9, equal_nan=True), \
                f"Q{qid}{note} col {gc}"
        else:
            assert (pd.Series(g).astype(str).to_numpy()
                    == pd.Series(w).astype(str).to_numpy()).all(), \
                f"Q{qid}{note} col {gc}"


def main():
    mesh = default_mesh()
    n_dev = int(mesh.devices.size)
    assert n_dev >= 2, f"smoke needs a multi-device mesh, got {n_dev}"

    data = generate_tpch(0.002, seed=3)
    plain = Context()
    dist = Context(mesh=mesh)
    for name, frame in data.items():
        plain.create_table(name, frame)
        dist.create_table(name, frame)

    refs = {qid: plain.sql(QUERIES[qid], return_futures=False)
            for qid in (1, 3, 6)}

    # 1+2: sharded Q1/Q3/Q6 match the single-device answers, with the
    # counters proving the sharded path (not a fallback) served them
    for qid in (1, 3, 6):
        before = spmd_counters()
        got = dist.sql(QUERIES[qid], return_futures=False)
        d = {k: v - before.get(k, 0) for k, v in spmd_counters().items()}
        assert d.get("spmd_queries", 0) == 1, f"Q{qid} not sharded: {d}"
        assert d.get("spmd_fallbacks", 0) == 0, f"Q{qid} fell back: {d}"
        assert d.get("spmd_partial_aggs", 0) >= 1, f"Q{qid}: {d}"
        if qid == 3:
            joins = (d.get("spmd_broadcast_joins", 0)
                     + d.get("spmd_exchange_joins", 0))
            assert joins >= 1, f"Q3 ran without a join collective: {d}"
            assert (d.get("spmd_exchanges", 0) == 0
                    or d.get("spmd_exchange_bytes", 0) > 0), \
                f"Q3 exchanged rows without byte accounting: {d}"
        check_frames(qid, got, refs[qid])
        print(f"  Q{qid} sharded over {n_dev} devices: match "
              f"({ {k: v for k, v in d.items() if v} })")

    # 3: a zero broadcast cap forces the hash-partitioned all_to_all
    # exchange variant on Q3's joins — same answer, exchange counters up
    os.environ["DSQL_SPMD_BROADCAST_ROWS"] = "0"
    try:
        before = spmd_counters()
        got = dist.sql(QUERIES[3], return_futures=False)
        d = {k: v - before.get(k, 0) for k, v in spmd_counters().items()}
        if d.get("spmd_queries", 0) == 1:
            assert d.get("spmd_exchange_joins", 0) >= 1, \
                f"broadcast cap 0 did not force the exchange join: {d}"
            assert d.get("spmd_exchange_bytes", 0) > 0, d
            check_frames(3, got, refs[3], note=" (forced exchange)")
            print(f"  Q3 forced-exchange: match "
                  f"({d.get('spmd_exchange_bytes', 0)} bytes moved)")
        else:
            # the exchange variant may legitimately refuse shapes the
            # broadcast variant accepts (e.g. a replicated build side);
            # the answer must still be right via the fallback
            check_frames(3, got, refs[3], note=" (forced exchange)")
            print("  Q3 forced-exchange: fell back (answer still correct)")
    finally:
        os.environ.pop("DSQL_SPMD_BROADCAST_ROWS", None)

    # 4: the kill switch restores the baseline path exactly
    os.environ["DSQL_MESH"] = "0"
    try:
        before = spmd_counters()
        for qid in (1, 6):
            got = dist.sql(QUERIES[qid], return_futures=False)
            check_frames(qid, got, refs[qid], note=" (DSQL_MESH=0)")
        d = {k: v - before.get(k, 0) for k, v in spmd_counters().items()
             if v != before.get(k, 0)}
        assert not d, f"DSQL_MESH=0 but spmd counters moved: {d}"
        print("  DSQL_MESH=0: baseline restored, no spmd counters")
    finally:
        os.environ.pop("DSQL_MESH", None)

    print("shard smoke OK")


if __name__ == "__main__":
    main()
