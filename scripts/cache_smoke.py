#!/usr/bin/env python
"""Result-cache smoke gate: the cache must serve, speed up, and invalidate.

Run by scripts/ci_local.sh (mirroring fault_smoke.py / obs_smoke.py):

    python scripts/cache_smoke.py

Asserts, against a real Context on generated data:

  1. an identical repeated query is a full-query cache hit
     (``last_report.cache["hit"]``) whose execute phase is >= 5x faster
     than the cold run — the hit skips device execution entirely;
  2. DDL on a referenced table (DROP + recreate with different data)
     invalidates: the next run is a miss and returns the NEW answer;
  3. the telemetry registry exposes the ``result_cache_*`` counters and
     gauges on the prometheus rendering (the /metrics surface);
  4. ``DSQL_RESULT_CACHE_MB=0`` disables the subsystem cleanly (no hit,
     no store, held memory released).

Exit 0 on success — if the cache silently rots (keys drift, epochs stop
bumping, hits stop landing), this gate fails loudly.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this gate asserts SYNCHRONOUS compile behavior; tiered execution
# (eager-first + background compile, on by default) is gated by
# scripts/warmstart_smoke.py instead
os.environ.setdefault("DSQL_TIERED", "0")
os.environ["DSQL_RESULT_CACHE_MB"] = "128"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import result_cache as rc  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

N = 400_000
QUERY = ("SELECT k, SUM(v) AS s, AVG(w) AS a FROM t "
         "GROUP BY k ORDER BY k")


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _frame(seed: int) -> pd.DataFrame:
    rng = np.random.RandomState(seed)
    return pd.DataFrame({
        "k": rng.randint(0, 50, N),
        "v": rng.randint(0, 1000, N),
        "w": rng.rand(N),
    })


def main() -> int:
    rc.get_cache().clear()
    ctx = Context()
    ctx.create_table("t", _frame(seed=1))

    # -- 1. cold run populates, warm run hits and skips execution ----------
    cold = ctx.sql(QUERY, return_futures=False)
    cold_rep = ctx.last_report
    if cold_rep.cache["hit"]:
        return fail("cold run reported a cache hit")
    if not cold_rep.cache["stored"]:
        return fail("cold run did not populate the cache")
    warm = ctx.sql(QUERY, return_futures=False)
    warm_rep = ctx.last_report
    if not warm_rep.cache["hit"]:
        return fail(f"warm run missed the cache: {warm_rep.cache}")
    if not cold.equals(warm):
        return fail("cached result differs from the computed one")
    cold_exec = cold_rep.phases.get("execute", 0.0)
    warm_exec = warm_rep.phases.get("execute", 1e9)
    if warm_exec * 5 > cold_exec:
        return fail(f"warm execute phase not >=5x faster: cold="
                    f"{cold_exec:.2f}ms warm={warm_exec:.2f}ms")
    print(f"ok hit: cold execute={cold_exec:.1f}ms warm={warm_exec:.2f}ms "
          f"({cold_exec / max(warm_exec, 1e-9):.0f}x) tier="
          f"{warm_rep.cache['tier']}")

    # -- 2. DDL invalidates: DROP + recreate with DIFFERENT data -----------
    ctx.sql("DROP TABLE t")
    ctx.create_table("t", _frame(seed=2))
    fresh = ctx.sql(QUERY, return_futures=False)
    fresh_rep = ctx.last_report
    if fresh_rep.cache["hit"]:
        return fail("query after DROP+recreate served a stale cached result")
    if fresh["s"].equals(cold["s"]):
        return fail("post-DDL result equals the old data's result")
    print("ok invalidation: post-DDL run recomputed on the new data")

    # -- 3. telemetry surface ----------------------------------------------
    text = tel.REGISTRY.render_prometheus()
    for name in ("dsql_result_cache_hits_total",
                 "dsql_result_cache_stores_total",
                 "dsql_result_cache_bytes"):
        if name not in text:
            return fail(f"{name} missing from the prometheus rendering")
    hits = tel.REGISTRY.get("result_cache_hits")
    if not hits or hits < 1:
        return fail("result_cache_hits counter did not advance")
    print("ok telemetry: result_cache_* counters + gauges exported")

    # -- 4. DSQL_RESULT_CACHE_MB=0 disables cleanly ------------------------
    os.environ["DSQL_RESULT_CACHE_MB"] = "0"
    try:
        before = tel.REGISTRY.get("result_cache_stores")
        off = ctx.sql(QUERY, return_futures=False)
        rep = ctx.last_report
        if rep.cache["hit"] or rep.cache["stored"]:
            return fail(f"cache active despite MB=0: {rep.cache}")
        if tel.REGISTRY.get("result_cache_stores") != before:
            return fail("store landed despite MB=0")
        if rc.get_cache().stats()["entries"]:
            return fail("disabled cache still holds entries")
        if not fresh.equals(off):
            return fail("cache-off result differs")
    finally:
        os.environ["DSQL_RESULT_CACHE_MB"] = "128"
    print("ok disable: MB=0 bypasses and releases the cache")

    print("result-cache smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
