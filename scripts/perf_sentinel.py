#!/usr/bin/env python3
"""Perf sentinel: diff a bench metrics JSON against the committed baseline.

ISSUE 13 satellite: the BENCH_r*.json trajectory is the repo's perf
memory, but nothing *reads* it in CI — a regression only surfaces when a
human eyeballs the artifacts.  This script closes that loop:

  1. pick a CURRENT metrics doc (``--current``, else the newest usable
     ``BENCH_r*.json`` in the repo root);
  2. pick a BASELINE (``--baseline``, else ``BASELINE.json``'s
     ``published`` headline when non-empty, else the newest usable
     ``BENCH_r*.json`` older than the current one);
  3. normalize both to the schema-versioned ``headline`` block bench.py
     emits (``schema``/``first_arrival_sec``/``program_store_hit_rate``/
     ``vs_pandas_geomean``/``warm_exec_geomean_sec``/``compile_errors``),
     deriving it from ``detail`` for pre-headline artifacts;
  4. compare every metric present on BOTH sides with a direction-aware
     tolerance band (``DSQL_SENTINEL_TOL``, default 0.25): lower-better
     metrics may not grow past base*(1+tol), higher-better may not fall
     below base*(1-tol), and ``compile_errors`` may never increase.

Exit 0 = within bands (or nothing comparable — a warning, not a failure:
old artifacts are sparse).  Exit 1 = regression, with a per-metric
verdict table on stdout.  ``--self-test`` doctors a 2x regression into a
copy of the current headline and asserts the comparison catches it.

Importable: ``extract_headline``/``compare``/``run`` are pure functions
used by tests/unit/test_profiler.py.
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

SENTINEL_SCHEMA = 1
HEADLINE_SCHEMA = 1

# direction of "better" per headline metric; anything not listed is
# reported but never judged
LOWER_BETTER = ("warm_exec_geomean_sec", "first_arrival_sec")
HIGHER_BETTER = ("program_store_hit_rate", "vs_pandas_geomean",
                 "param_plan_hit_rate")
NO_INCREASE = ("compile_errors",)
# headline fields shown as context but NEVER gated on: the watchtower's
# per-class SLO attainment depends on the burst pass's load shape, so a
# band would flap — operators read the trend, the sentinel only displays
INFORMATIONAL = ("slo_attainment", "autopilot_vs_tuned_geomean")

# the wall-clock metric name bench.py has emitted since PR 6; artifacts
# with a different ``metric`` (r01's rows/sec era) contribute no
# warm_exec number
_WALL_METRIC = "tpch_q1_q22_geomean_wall"


def default_tolerance() -> float:
    try:
        raw = os.environ.get("DSQL_SENTINEL_TOL", "")
        return float(raw) if raw else 0.25
    except ValueError:
        return 0.25


def _geomean(vals) -> Optional[float]:
    vals = [float(v) for v in vals if v and float(v) > 0]
    if not vals:
        return None
    return math.exp(sum(math.log(v) for v in vals) / len(vals))


def _unwrap(doc: dict) -> Optional[dict]:
    """The metrics object itself: bench artifacts wrap it in
    ``{"n":..,"cmd":..,"parsed":{...}}``; bench_result.json is bare."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("parsed"), dict):
        return doc["parsed"]
    if "metric" in doc or "headline" in doc or "detail" in doc:
        return doc
    return None


def extract_headline(doc: dict) -> Optional[Dict[str, object]]:
    """Normalize any bench artifact (wrapped or bare, pre- or
    post-headline) to the headline block.  None when nothing usable."""
    obj = _unwrap(doc)
    if obj is None:
        return None
    hl = obj.get("headline")
    if isinstance(hl, dict):
        out = dict(hl)
        out.setdefault("schema", HEADLINE_SCHEMA)
        return out
    # pre-headline artifact: derive from detail
    det = obj.get("detail") or {}
    if not isinstance(det, dict):
        det = {}
    out: Dict[str, object] = {"schema": HEADLINE_SCHEMA}
    value = obj.get("value")
    out["warm_exec_geomean_sec"] = (
        float(value) if obj.get("metric") == _WALL_METRIC
        and isinstance(value, (int, float)) and value > 0 else None)
    fa = det.get("first_arrival_sec")
    out["first_arrival_sec"] = (_geomean(fa.values())
                                if isinstance(fa, dict) else None)
    out["program_store_hit_rate"] = det.get("program_store_hit_rate")
    # detail.vs_pandas_geomean is the same number as top-level
    # vs_baseline (bench.py keeps both); accept either
    vsp = det.get("vs_pandas_geomean")
    if vsp is None:
        vb = obj.get("vs_baseline")
        vsp = float(vb) if isinstance(vb, (int, float)) and vb > 0 else None
    out["vs_pandas_geomean"] = vsp
    out["slo_attainment"] = det.get("slo_attainment")
    cs = det.get("compiled_stats") or {}
    ce = cs.get("compile_errors") if isinstance(cs, dict) else None
    out["compile_errors"] = int(ce) if ce is not None else None
    if all(out.get(k) is None for k in
           LOWER_BETTER + HIGHER_BETTER + NO_INCREASE):
        return None
    return out


def compare(baseline: Dict[str, object], current: Dict[str, object],
            tol: float) -> Tuple[List[dict], List[dict]]:
    """(regressions, verdicts): every metric present and non-None on both
    sides gets a verdict row; rows breaching their band also land in
    regressions."""
    regressions: List[dict] = []
    verdicts: List[dict] = []
    for key in LOWER_BETTER + HIGHER_BETTER + NO_INCREASE:
        b, c = baseline.get(key), current.get(key)
        if b is None or c is None:
            continue
        b, c = float(b), float(c)
        row = {"metric": key, "baseline": b, "current": c}
        if key in NO_INCREASE:
            row["band"] = f"<= {b:g}"
            row["ok"] = c <= b
        elif key in LOWER_BETTER:
            limit = b * (1.0 + tol)
            row["band"] = f"<= {limit:.4g}"
            row["ok"] = c <= limit
        else:
            limit = b * (1.0 - tol)
            row["band"] = f">= {limit:.4g}"
            row["ok"] = c >= limit
        verdicts.append(row)
        if not row["ok"]:
            regressions.append(row)
    return regressions, verdicts


def _bench_files(root: str) -> List[str]:
    """BENCH_r*.json in run order (r01, r02, ... — lexicographic works
    for the zero-padded names; fall back to numeric sort)."""
    files = glob.glob(os.path.join(root, "BENCH_r*.json"))

    def keyfn(p):
        m = re.search(r"BENCH_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else 0

    return sorted(files, key=keyfn)


def _load(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def resolve_inputs(root: str, current_path: Optional[str],
                   baseline_path: Optional[str]
                   ) -> Tuple[Optional[dict], str, Optional[dict], str]:
    """(current_headline, current_label, baseline_headline,
    baseline_label) per the precedence in the module docstring."""
    usable: List[Tuple[str, dict]] = []
    for p in _bench_files(root):
        doc = _load(p)
        hl = extract_headline(doc) if doc else None
        if hl is not None:
            usable.append((p, hl))

    cur_hl, cur_label = None, "(none)"
    if current_path:
        cur_hl = extract_headline(_load(current_path) or {})
        cur_label = current_path
    elif usable:
        cur_label, cur_hl = usable[-1]
        usable = usable[:-1]
    elif current_path is None:
        pass

    base_hl, base_label = None, "(none)"
    if baseline_path:
        base_hl = extract_headline(_load(baseline_path) or {})
        base_label = baseline_path
    else:
        bl = _load(os.path.join(root, "BASELINE.json")) or {}
        published = bl.get("published")
        if isinstance(published, dict) and published:
            base_hl = extract_headline({"headline": published}) \
                or extract_headline(published)
            base_label = "BASELINE.json:published"
        if base_hl is None and usable:
            # newest usable artifact older than the current one
            base_label, base_hl = usable[-1]
    return cur_hl, cur_label, base_hl, base_label


def run(root: str, current_path: Optional[str] = None,
        baseline_path: Optional[str] = None,
        tol: Optional[float] = None) -> Tuple[int, dict]:
    """(exit_code, report).  0 = pass (or nothing comparable), 1 =
    regression, 2 = requested input unreadable."""
    tol = default_tolerance() if tol is None else tol
    cur, cur_label, base, base_label = resolve_inputs(
        root, current_path, baseline_path)
    report = {"sentinel_schema": SENTINEL_SCHEMA, "tolerance": tol,
              "current": cur_label, "baseline": base_label,
              "current_headline": cur, "baseline_headline": base,
              "verdicts": [], "regressions": [], "status": "pass"}
    if current_path and cur is None:
        report["status"] = "error: current metrics unreadable"
        return 2, report
    if baseline_path and base is None:
        report["status"] = "error: baseline metrics unreadable"
        return 2, report
    if cur is None or base is None:
        report["status"] = "pass (nothing comparable)"
        return 0, report
    regressions, verdicts = compare(base, cur, tol)
    report["verdicts"] = verdicts
    report["regressions"] = regressions
    if not verdicts:
        report["status"] = "pass (no shared metrics)"
    elif regressions:
        report["status"] = "REGRESSION"
        return 1, report
    return 0, report


def _render(report: dict) -> str:
    lines = [f"perf_sentinel schema={report['sentinel_schema']} "
             f"tol={report['tolerance']:g}",
             f"  baseline: {report['baseline']}",
             f"  current:  {report['current']}"]
    for row in report["verdicts"]:
        mark = "ok  " if row["ok"] else "FAIL"
        lines.append(f"  [{mark}] {row['metric']}: "
                     f"{row['baseline']:g} -> {row['current']:g} "
                     f"(band {row['band']})")
    for key in INFORMATIONAL:
        b = (report.get("baseline_headline") or {}).get(key)
        c = (report.get("current_headline") or {}).get(key)
        if b is None and c is None:
            continue

        def fmt(v):
            if isinstance(v, dict):
                return "{" + ", ".join(f"{k}={v[k]:g}"
                                       for k in sorted(v)) + "}"
            return "n/a" if v is None else f"{v:g}"

        lines.append(f"  [info] {key}: {fmt(b)} -> {fmt(c)} "
                     f"(informational, non-gating)")
    lines.append(f"  status: {report['status']}")
    return "\n".join(lines)


def self_test(root: str) -> int:
    """Doctor a 2x regression into the current headline and assert the
    comparison catches it (and that an identical headline passes)."""
    cur, label, _, _ = resolve_inputs(root, None, None)
    if cur is None or all(cur.get(k) is None for k in LOWER_BETTER):
        # no wall-clock artifact to doctor: use a synthetic one so the
        # self-test still exercises the comparator
        cur, label = {"schema": HEADLINE_SCHEMA,
                      "warm_exec_geomean_sec": 1.0,
                      "first_arrival_sec": 2.0,
                      "program_store_hit_rate": 0.9,
                      "vs_pandas_geomean": 1.5,
                      "compile_errors": 0}, "(synthetic)"
    same, _ = compare(cur, dict(cur), default_tolerance())
    if same:
        print(f"self-test FAIL: identical headline flagged ({same})")
        return 1
    doctored = dict(cur)
    hit = False
    for k in LOWER_BETTER:
        if doctored.get(k) is not None:
            doctored[k] = float(doctored[k]) * 2.0
            hit = True
    for k in HIGHER_BETTER:
        if doctored.get(k) is not None:
            doctored[k] = float(doctored[k]) / 2.0
            hit = True
    if not hit:
        print("self-test FAIL: headline has no doctorable metric")
        return 1
    regressions, _ = compare(cur, doctored, default_tolerance())
    if not regressions:
        print("self-test FAIL: 2x regression not flagged")
        return 1
    print(f"self-test ok: 2x regression on {label} flagged "
          f"{len(regressions)} metric(s)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--current", help="metrics JSON to judge (default: "
                    "newest usable BENCH_r*.json)")
    ap.add_argument("--baseline", help="metrics JSON to judge against "
                    "(default: BASELINE.json published headline, else "
                    "the previous usable BENCH_r*.json)")
    ap.add_argument("--tol", type=float, default=None,
                    help="relative tolerance band (default: "
                    "DSQL_SENTINEL_TOL or 0.25)")
    ap.add_argument("--root", default=None,
                    help="repo root holding BENCH_r*.json/BASELINE.json")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of text")
    ap.add_argument("--self-test", action="store_true",
                    help="inject a 2x regression and assert it is caught")
    args = ap.parse_args(argv)
    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    if args.self_test:
        return self_test(root)
    code, report = run(root, args.current, args.baseline, args.tol)
    print(json.dumps(report) if args.json else _render(report))
    return code


if __name__ == "__main__":
    sys.exit(main())
