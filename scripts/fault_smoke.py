#!/usr/bin/env python
"""Fault-injection smoke gate: a TPC-H subset under an injected compile
fault must still produce oracle-correct results via the resilience ladder.

Run by scripts/tier1.sh (and CI) as

    DSQL_FAULT_INJECT=compile:1 python scripts/fault_smoke.py

The spec makes the FIRST compile attempt of every query fail; the engine
must retry (or degrade) and return the same answer the eager executor
gives with no fault armed — and ``compiled.stats`` must show the ladder
actually ran (retries/degradations + fault_* counters), or the injection
sites have silently rotted.  Any other spec (e.g. ``compile:1+`` to force
full ladder walks, ``materialize:1``) can be passed through the same env
var.  Exit 0 on success.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# this gate asserts SYNCHRONOUS compile behavior; tiered execution
# (eager-first + background compile, on by default) is gated by
# scripts/warmstart_smoke.py instead
os.environ.setdefault("DSQL_TIERED", "0")
os.environ.setdefault("DSQL_FAULT_INJECT", "compile:1")
os.environ.setdefault("DSQL_RETRY_BASE_MS", "1")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import pandas as pd  # noqa: E402

from benchmarks.tpch import QUERIES, generate_tpch  # noqa: E402
from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.physical import compiled  # noqa: E402
from dask_sql_tpu.runtime import faults  # noqa: E402

# agg-heavy (Q1), join+agg+topk (Q3), scan/filter (Q6): small but covers
# the single-program, staged and filter-only compile shapes
SUBSET = (1, 3, 6)
SF = 0.002


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64")
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def main() -> int:
    spec = os.environ["DSQL_FAULT_INJECT"]
    data = generate_tpch(SF)
    ctx = Context()
    for name, df in data.items():
        ctx.create_table(name, df)

    failures = 0
    for qid in SUBSET:
        q = QUERIES[qid]
        # fresh per-site counters: the spec fires on each query's first
        # compile, not only once per process
        faults.reset()
        s0 = {k: compiled.stats[k] for k in
              ("retries", "degradations", "fault_compile")}
        got = ctx.sql(q, return_futures=False)

        # oracle: the eager executor, faults disarmed
        del os.environ["DSQL_FAULT_INJECT"]
        os.environ["DSQL_COMPILE"] = "0"
        try:
            want = ctx.sql(q, return_futures=False)
        finally:
            del os.environ["DSQL_COMPILE"]
            os.environ["DSQL_FAULT_INJECT"] = spec

        fired = compiled.stats["fault_compile"] - s0["fault_compile"]
        recovered = (compiled.stats["retries"] - s0["retries"]
                     + compiled.stats["degradations"] - s0["degradations"])
        try:
            pd.testing.assert_frame_equal(_norm(got), _norm(want),
                                          check_dtype=False, rtol=1e-6,
                                          atol=1e-10)
        except AssertionError as e:
            print(f"FAIL q{qid}: wrong result under {spec}\n{e}")
            failures += 1
            continue
        if fired == 0 or recovered == 0:
            print(f"FAIL q{qid}: fault did not exercise the ladder "
                  f"(fired={fired}, retries+degradations={recovered})")
            failures += 1
            continue
        print(f"ok q{qid}: correct under {spec} "
              f"(fired={fired}, retries+degradations={recovered})")
    if failures:
        print(f"fault smoke FAILED ({failures}/{len(SUBSET)} queries)")
        return 1
    print("fault smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
