#!/usr/bin/env python
"""Statistics/adaptive-operator smoke gate (runtime/statistics.py).

Run by scripts/ci_local.sh (mirroring cache_smoke.py / sched_smoke.py):

    python scripts/stats_smoke.py

Asserts, against a real Context on generated data:

  1. **dense beats hash** on a dense-small-domain-key aggregate: the
     direct-index eager path (DSQL_FORCE_GROUPBY=dense) is faster than
     forced hash aggregation, best-of-N on a ~2M-row table — the perf
     claim the crossover table encodes, measured, not assumed;
  2. all three forced variants return IDENTICAL answers (the dispatch is
     a pure perf decision);
  3. **join reorder picks the smaller build side**: a 3-table comma
     chain listed fact-first is rewritten so the fact table is attached
     LAST, visible in EXPLAIN and in the
     ``operator_choice_join_order_stats`` counter;
  4. adaptive dispatch fires on its own (no forcing): the dense counter
     moves and EXPLAIN carries the ``-- operator:`` trailer;
  5. ``DSQL_ADAPTIVE=0`` restores the baseline: same answers, no
     adaptive counters, no EXPLAIN trailer.

Exit 0 on success — if stats collection drifts, the crossover stops
firing, or the kill switch stops killing, this gate fails loudly.
"""
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# eager timing is the point: the compiled path fuses the plan and never
# reaches the eager dispatch this gate measures
os.environ["DSQL_COMPILE"] = "0"
os.environ["DSQL_TIERED"] = "0"
os.environ["DSQL_RESULT_CACHE_MB"] = "0"
os.environ["DSQL_MAX_CONCURRENT_QUERIES"] = "0"
os.environ.pop("DSQL_ADAPTIVE", None)
os.environ.pop("DSQL_FORCE_GROUPBY", None)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

N = 2_000_000
DOMAIN = 512
AGG = "SELECT k, SUM(v) AS s, COUNT(*) AS n FROM t GROUP BY k"
BEST_OF = 5


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def counters():
    return dict(tel.REGISTRY.counters())


def delta(before, key):
    return tel.REGISTRY.counters().get(key, 0) - before.get(key, 0)


def best_of(fn, n=BEST_OF):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    rng = np.random.RandomState(11)
    ctx = Context()
    ctx.create_table("t", pd.DataFrame({
        "k": rng.randint(0, DOMAIN, N).astype("int64"),
        "v": rng.rand(N),
    }))

    # -- 2: forced-variant agreement -------------------------------------
    results = {}
    for variant in ("hash", "sorted", "dense"):
        os.environ["DSQL_FORCE_GROUPBY"] = variant
        results[variant] = (ctx.sql(AGG).to_pandas()
                            .sort_values("k").reset_index(drop=True))
    base = results["hash"]
    for variant in ("sorted", "dense"):
        try:
            pd.testing.assert_frame_equal(results[variant], base,
                                          check_dtype=False, rtol=1e-9)
        except AssertionError as e:
            return fail(f"forced {variant} disagrees with hash: {e}")
    print(f"variant agreement OK ({len(base)} groups)")

    # -- 1: dense beats hash on the dense-key aggregate ------------------
    timings = {}
    for variant in ("hash", "dense"):
        os.environ["DSQL_FORCE_GROUPBY"] = variant
        ctx.sql(AGG)  # warm (tracing/alloc noise out of the measurement)
        timings[variant] = best_of(lambda: ctx.sql(AGG))
    os.environ.pop("DSQL_FORCE_GROUPBY", None)
    print(f"dense={timings['dense'] * 1e3:.1f}ms "
          f"hash={timings['hash'] * 1e3:.1f}ms "
          f"(x{timings['hash'] / timings['dense']:.2f})")
    if timings["dense"] >= timings["hash"]:
        return fail(
            f"dense ({timings['dense'] * 1e3:.1f}ms) not faster than hash "
            f"({timings['hash'] * 1e3:.1f}ms) on a {N}-row dense-key "
            f"aggregate (domain={DOMAIN})")

    # -- 4: adaptive dispatch fires unforced -----------------------------
    before = counters()
    ctx.sql(AGG)
    if delta(before, "operator_choice_groupby_dense") < 1:
        return fail("adaptive dispatch did not pick dense unforced")
    text = ctx.sql("EXPLAIN " + AGG).to_pandas()["PLAN"].str.cat(sep="\n")
    if "-- operator: groupby=dense" not in text:
        return fail(f"EXPLAIN lacks the operator trailer:\n{text}")
    print("adaptive dispatch + EXPLAIN trailer OK")

    # -- 3: join reorder attaches the big side last ----------------------
    fact = pd.DataFrame({"k": rng.randint(0, 1000, 500_000)})
    dim = pd.DataFrame({"k": np.arange(1000),
                        "d": np.arange(1000) % 20})
    tiny = pd.DataFrame({"d": np.arange(20)})
    ctx.create_table("fact", fact)
    ctx.create_table("dim", dim)
    ctx.create_table("tiny", tiny)
    q3 = ("SELECT COUNT(*) AS c FROM fact, dim, tiny "
          "WHERE fact.k = dim.k AND dim.d = tiny.d")
    before = counters()
    got = int(ctx.sql(q3).to_pandas()["c"][0])
    exp = len(fact.merge(dim, on="k").merge(tiny, on="d"))
    if got != exp:
        return fail(f"reordered 3-way join wrong: {got} != {exp}")
    if delta(before, "operator_choice_join_order_stats") < 1:
        return fail("stats join reorder did not fire on a fact-first chain")
    plan_text = ctx.sql("EXPLAIN " + q3) \
                   .to_pandas()["PLAN"].str.cat(sep="\n")
    if plan_text.index("fact") < plan_text.index("dim"):
        return fail(f"fact table still leads the join chain:\n{plan_text}")
    print("join reorder OK (fact attached last, answer exact)")

    # -- 5: the kill switch restores the baseline ------------------------
    os.environ["DSQL_ADAPTIVE"] = "0"
    before = counters()
    off = (ctx.sql(AGG).to_pandas().sort_values("k")
           .reset_index(drop=True))
    pd.testing.assert_frame_equal(off, base, check_dtype=False, rtol=1e-9)
    for key in ("operator_choice_groupby_dense",
                "operator_choice_groupby_sorted",
                "operator_choice_join_order_stats"):
        if delta(before, key):
            return fail(f"DSQL_ADAPTIVE=0 still moved {key}")
    text = ctx.sql("EXPLAIN " + AGG).to_pandas()["PLAN"].str.cat(sep="\n")
    if "-- operator:" in text:
        return fail("DSQL_ADAPTIVE=0 still prints operator trailers")
    print("kill switch OK (baseline answers, silent counters)")

    print("stats smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
