#!/usr/bin/env python
"""Continuous-ingestion smoke gate: WAL-backed appends must be durable,
O(delta) for join/DISTINCT views, snapshot-consistent, and bit-for-bit
absent when disarmed.

Run by scripts/ci_local.sh (mirroring mv_smoke.py / fleet_smoke.py):

    python scripts/ingest_smoke.py

Asserts, against real Contexts with ``DSQL_INGEST_DIR`` armed:

  1. sustained appends through the ingest log keep a delta-join view and
     a COUNT(DISTINCT) view pandas-oracle exact, with every refresh
     incremental (mv_refresh_full never moves after the builds);
  2. after a 1k-row append into a ~400k-row join, the maintained refresh
     is >= 5x faster than recomputing the defining join query;
  3. snapshot isolation: under a live writer committing multi-row
     batches, a reader that scans the table twice in one query (scalar
     subquery + outer scan) never sees two different prefixes, and no
     read ever observes a partial batch;
  4. kill -9 durability: a writer child killed mid-stream loses ZERO
     acked batches — a fresh process replays the WAL to an exact
     batch-aligned row count;
  5. ``DSQL_INGEST=0`` (and an unset dir) keep runtime/ingest.py
     un-imported with appends still working — the pre-subsystem
     baseline, proven in subprocesses.

Exit 0 on success.
"""
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("DSQL_TIERED", "0")
# maintained view state is a result-cache tenant
os.environ["DSQL_RESULT_CACHE_MB"] = "256"

ROOT = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

N_FACT = 400_000
N_DIM = 1_000
DELTA = 1_000
JOIN_SQL = ("SELECT f.k AS k, f.x AS x, d.grp AS grp "
            "FROM f INNER JOIN d ON f.k = d.k")
CD_SQL = "SELECT COUNT(DISTINCT k) AS n FROM f"


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _fact(n: int, seed: int) -> pd.DataFrame:
    rng = np.random.RandomState(seed)
    return pd.DataFrame({"k": rng.randint(0, N_DIM, n),
                         "x": rng.rand(n) * 100})


def _join_oracle(fact: pd.DataFrame, dim: pd.DataFrame) -> pd.DataFrame:
    m = fact.merge(dim, on="k", how="inner")[["k", "x", "grp"]]
    return m.sort_values(["k", "x", "grp"]).reset_index(drop=True)


def _check_views(ctx, fact, dim, what):
    got = ctx.sql("SELECT * FROM vj", return_futures=False)
    got = got[["k", "x", "grp"]].sort_values(
        ["k", "x", "grp"]).reset_index(drop=True)
    pd.testing.assert_frame_equal(got, _join_oracle(fact, dim),
                                  check_dtype=False, check_exact=False)
    n = ctx.sql("SELECT n FROM vd", return_futures=False)
    if int(n["n"][0]) != fact["k"].nunique():
        return fail(f"{what}: COUNT(DISTINCT) view wrong: "
                    f"{int(n['n'][0])} != {fact['k'].nunique()}")
    print(f"ok oracle: {what} ({len(fact)} fact rows)")
    return None


def main() -> int:
    wal_root = tempfile.mkdtemp(prefix="dsql_ingest_smoke_")
    os.environ["DSQL_INGEST_DIR"] = os.path.join(wal_root, "a")

    from dask_sql_tpu import Context
    from dask_sql_tpu.runtime import ingest, telemetry as tel

    ctx = Context()
    fact = _fact(N_FACT, seed=1)
    dim = pd.DataFrame({"k": np.arange(N_DIM),
                        "grp": np.arange(N_DIM) % 7})
    ctx.create_table("f", fact)
    ctx.create_table("d", dim)
    ctx.sql(f"CREATE MATERIALIZED VIEW vj AS {JOIN_SQL}")
    ctx.sql(f"CREATE MATERIALIZED VIEW vd AS {CD_SQL}")

    # -- 1. sustained appends, oracle-exact, all-incremental ---------------
    # warm-up append pays the one-time XLA compiles for the delta plans
    warm = _fact(DELTA, seed=90)
    ctx.append_rows("f", warm)
    fact = pd.concat([fact, warm], ignore_index=True)
    r = _check_views(ctx, fact, dim, "warm-up append")
    if r is not None:
        return r
    full0 = tel.REGISTRY.get("mv_refresh_full", 0)
    inc0 = tel.REGISTRY.get("mv_refresh_incremental", 0)
    for i in range(2, 5):
        delta = _fact(DELTA, seed=i)
        ctx.append_rows("f", delta)
        fact = pd.concat([fact, delta], ignore_index=True)
        r = _check_views(ctx, fact, dim, f"append #{i - 1}")
        if r is not None:
            return r
    if tel.REGISTRY.get("mv_refresh_full", 0) != full0:
        return fail("a sustained append degraded to a full recompute")
    inc_moved = tel.REGISTRY.get("mv_refresh_incremental", 0) - inc0
    if inc_moved < 6:  # 3 appends x 2 views
        return fail(f"expected >=6 incremental refreshes, saw {inc_moved}")
    print(f"ok incremental: {inc_moved} refreshes, 0 full recomputes")

    # -- 2. speed: maintained join refresh vs recompute --------------------
    delta = _fact(DELTA, seed=7)
    ctx.append_rows("f", delta)
    fact = pd.concat([fact, delta], ignore_index=True)
    t0 = time.perf_counter()
    ctx.sql("REFRESH MATERIALIZED VIEW vj")
    refresh_sec = time.perf_counter() - t0
    t0 = time.perf_counter()
    recomputed = ctx.sql(JOIN_SQL, return_futures=False)
    recompute_sec = time.perf_counter() - t0
    if len(recomputed) != len(fact):
        return fail("recompute control query returned wrong row count")
    if refresh_sec * 5 > recompute_sec:
        return fail(f"delta-join refresh not >=5x faster: refresh="
                    f"{refresh_sec * 1e3:.1f}ms recompute="
                    f"{recompute_sec * 1e3:.1f}ms")
    print(f"ok speed: refresh={refresh_sec * 1e3:.1f}ms recompute="
          f"{recompute_sec * 1e3:.1f}ms "
          f"({recompute_sec / max(refresh_sec, 1e-9):.0f}x)")

    # -- 3. snapshot isolation under a live writer -------------------------
    batch = 4
    ctx.create_table("s", pd.DataFrame({"a": np.arange(batch * 2)}))
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        while not stop.is_set():
            try:
                ctx.append_rows(
                    "s", [(int(v),) for v in range(i, i + batch)])
            except Exception as e:  # pragma: no cover
                errs.append(e)
                return
            i += batch

    w = threading.Thread(target=writer, daemon=True)
    w.start()
    deadline = time.monotonic() + 3.0
    reads = 0
    last = 0
    while time.monotonic() < deadline:
        out = ctx.sql("SELECT (SELECT COUNT(*) FROM s) - COUNT(*) AS d, "
                      "COUNT(*) AS n FROM s", return_futures=False)
        if int(out["d"][0]) != 0:
            stop.set()
            return fail("two scans of one query saw different prefixes "
                        f"(d={int(out['d'][0])})")
        n = int(out["n"][0])
        if n % batch != 0:
            stop.set()
            return fail(f"read observed a partial batch (n={n})")
        if n < last:
            stop.set()
            return fail(f"reads went backwards ({last} -> {n})")
        last = n
        reads += 1
    stop.set()
    w.join(timeout=5)
    if errs:
        return fail(f"writer died: {errs[0]!r}")
    print(f"ok snapshot: {reads} consistent reads beside a live writer "
          f"({last} rows committed)")

    # -- 4. kill -9 loses zero acked batches -------------------------------
    kill_dir = os.path.join(wal_root, "k")
    child_src = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import pandas as pd
from dask_sql_tpu import Context
c = Context()
c.create_table("wal_t", pd.DataFrame({"a": list(range(10))}))
i = 0
while True:
    c.append_rows("wal_t", [(i * 5 + j,) for j in range(5)])
    i += 1
    print(f"ACK {i}", flush=True)
"""
    env = dict(os.environ, DSQL_INGEST_DIR=kill_dir, PYTHONPATH=ROOT)
    child = subprocess.Popen([sys.executable, "-c", child_src], env=env,
                             stdout=subprocess.PIPE, text=True, cwd=ROOT)
    acked = 0
    try:
        for line in child.stdout:
            if line.startswith("ACK"):
                acked = int(line.split()[1])
            if acked >= 6:
                break
        os.kill(child.pid, signal.SIGKILL)
    finally:
        child.wait(timeout=30)
    if acked < 6:
        return fail("writer child never acked 6 batches")

    os.environ["DSQL_INGEST_DIR"] = kill_dir
    replayed0 = tel.REGISTRY.get("ingest_replayed_batches", 0)
    rec = Context()
    rec.create_table("wal_t", pd.DataFrame({"a": list(range(10))}))
    n = int(rec.sql("SELECT COUNT(*) AS n FROM wal_t",
                    return_futures=False)["n"][0])
    if n < 10 + acked * 5:
        return fail(f"kill -9 lost acked batches: {n} rows < "
                    f"{10 + acked * 5}")
    if (n - 10) % 5 != 0:
        return fail(f"replay surfaced a partial batch ({n} rows)")
    batches = tel.REGISTRY.get("ingest_replayed_batches", 0) - replayed0
    print(f"ok durability: kill -9 after {acked} acks -> {batches} "
          f"batches replayed, {n} rows (batch-aligned)")

    # -- 5. disarmed = bit-for-bit baseline, module never imported ---------
    probe = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import pandas as pd\n"
        "from dask_sql_tpu import Context\n"
        "c = Context()\n"
        "c.create_table('t', pd.DataFrame({'a': [1, 2, 3]}))\n"
        "assert c.append_rows('t', [(4,)]) == 1\n"
        "out = c.sql('SELECT SUM(a) AS s FROM t', return_futures=False)\n"
        "assert int(out['s'][0]) == 10, out\n"
        "assert 'dask_sql_tpu.runtime.ingest' not in sys.modules\n"
        "print('BASELINE OK')\n")
    for label, tweak in (("DSQL_INGEST=0", {"DSQL_INGEST": "0"}),
                         ("unset dir", {"DSQL_INGEST_DIR": None})):
        env = dict(os.environ, PYTHONPATH=ROOT)
        for k, v in tweak.items():
            if v is None:
                env.pop(k, None)
            else:
                env[k] = v
        out = subprocess.run([sys.executable, "-c", probe], env=env,
                             capture_output=True, text=True, cwd=ROOT,
                             timeout=120)
        if out.returncode != 0 or "BASELINE OK" not in out.stdout:
            return fail(f"disarmed baseline ({label}) broke:\n"
                        f"{out.stdout}\n{out.stderr}")
    print("ok disarmed: ingest module never imported, appends still work")

    ingest._reset_for_tests()
    print("ingest smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
