#!/usr/bin/env python
"""Autopilot smoke gate: the closed loop from watchtower to optimizer.

Run by scripts/ci_local.sh (mirroring scripts/mv_smoke.py):

    python scripts/autopilot_smoke.py

A shifting workload must CONVERGE under autopilot with zero operator
involvement:

  1. a repeated aggregate becomes the top ``system.view_candidates``
     entry and is auto-materialized within N queries (one tick), the
     action journaled and visible through ``SELECT ... FROM
     system.autopilot``;
  2. after a base-table append the repeat is served from the maintained
     view (O(delta) refresh, serve counter advances) and the answer
     stays pandas-oracle exact;
  3. when the workload shifts away, the now-cold view is dropped and
     its budget share freed;
  4. a skewed grace-hash join trips ``DSQL_AUTOPILOT_SKEW``, records a
     re-plan hint, and the NEXT execution runs with the flipped
     partitioning, measures FASTER than the recorded baseline, and
     journals the verdict — still oracle-exact;
  5. ``DSQL_AUTOPILOT=0`` is a silent baseline: no ticks, no journal,
     no counters, answers unchanged.

Exit 0 on success — if the advisor stops acting (or starts acting
wrongly), this gate fails loudly.
"""
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
WORK_DIR = tempfile.mkdtemp(prefix="dsql_autopilot_")
os.environ["DSQL_HISTORY_FILE"] = os.path.join(WORK_DIR, "history.jsonl")
os.environ["DSQL_SPILL_DIR"] = os.path.join(WORK_DIR, "spill")
os.environ["DSQL_SPILL_MB"] = "64"
os.environ["DSQL_AUTOPILOT"] = "1"
os.environ["DSQL_AUTOPILOT_INTERVAL_S"] = "0"   # explicit ticks: determinism
os.environ["DSQL_AUTOPILOT_MIN_HITS"] = "2"
os.environ["DSQL_AUTOPILOT_SKEW"] = "1.5"

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

from dask_sql_tpu import Context  # noqa: E402
from dask_sql_tpu.runtime import autopilot as ap  # noqa: E402
from dask_sql_tpu.runtime import telemetry as tel  # noqa: E402

HOT_SQL = "SELECT a, SUM(b) AS s, COUNT(*) AS n FROM t GROUP BY a"


def fail(msg: str) -> int:
    print(f"FAIL: {msg}")
    return 1


def _ctr(name: str) -> int:
    return tel.REGISTRY.get(name) or 0


def _oracle(frame: pd.DataFrame) -> pd.DataFrame:
    g = frame.groupby("a", as_index=False).agg(s=("b", "sum"), n=("b", "size"))
    return g.sort_values("a").reset_index(drop=True)


def _norm(df: pd.DataFrame) -> pd.DataFrame:
    out = df.copy()
    for col in out.columns:
        if out[col].dtype.kind in "iuf":
            out[col] = out[col].astype("float64").round(6)
    return (out.sort_values(list(out.columns), na_position="last")
               .reset_index(drop=True))


def _exact(got, want, what: str):
    pd.testing.assert_frame_equal(_norm(got), _norm(want),
                                  check_dtype=False, rtol=1e-6, atol=1e-9,
                                  obj=what)


def main() -> int:
    rng = np.random.default_rng(0)

    # -- 1. convergence: repeated aggregate auto-materializes --------------
    ctx = Context()
    base = pd.DataFrame({"a": rng.integers(0, 8, 50_000),
                         "b": np.round(rng.random(50_000) * 100, 3)})
    ctx.create_table("t", base)
    for _ in range(3):
        got = ctx.sql(HOT_SQL, return_futures=False)
    _exact(got, _oracle(base), "hot aggregate")
    now = time.time()
    out = ap.tick(ctx, now=now)
    if out.get("created") != 1:
        return fail(f"tick did not materialize the top candidate: {out}")
    sysrows = ctx.sql(
        "SELECT action, fingerprint FROM system.autopilot",
        return_futures=False)
    if "mv_create" not in set(sysrows["action"]):
        return fail(f"mv_create not visible in system.autopilot: {sysrows}")
    view = ap.engine_section()["managedViews"][0]
    print(f"ok converge: {view} auto-materialized after 3 queries "
          f"(journaled, in system.autopilot)")

    # -- 2. serve across an append: O(delta) refresh, oracle exact ---------
    extra = pd.DataFrame({"a": [0, 1, 2], "b": [1000.0, 2000.0, 3000.0]})
    ctx.append_rows("t", extra)
    serves0 = _ctr("autopilot_mv_serves")
    got = ctx.sql(HOT_SQL, return_futures=False)
    if _ctr("autopilot_mv_serves") != serves0 + 1:
        return fail("append + repeat was not served from the managed view")
    _exact(got, _oracle(pd.concat([base, extra], ignore_index=True)),
           "served repeat")
    print("ok serve: repeat after append answered from the maintained "
          "view, pandas-exact")

    # -- 3. workload shifts away: the cold view is dropped -----------------
    ap.tick(ctx, now=now + 1)       # absorb the serve above into the books
    out = ap.tick(ctx, now=now + 3600)
    if out.get("dropped") != 1:
        return fail(f"cold view not dropped: {out}")
    if ap.engine_section()["mvUsedBytes"] != 0:
        return fail("drop did not free the budget share")
    if not any(r["action"] == "mv_drop" for r in ap.journal_rows()):
        return fail("mv_drop not journaled")
    print("ok cold drop: unused view dropped, budget freed, journaled")

    # -- 4. skew -> hint -> next run flips partitioning and measures faster
    n_fact, n_dim = 6_000, 1_000
    key = rng.integers(0, n_dim, n_fact).astype("float64")
    key[rng.random(n_fact) < 0.9] = 3.0         # 90% of rows on one key
    fact = pd.DataFrame({"fk": key,
                         "val": np.round(rng.random(n_fact) * 100, 3)})
    dim = pd.DataFrame({"dk": np.arange(n_dim),
                        "w": np.round(rng.random(n_dim) * 10, 3)})
    jctx = Context()
    jctx.create_table("fact", fact, chunked=True, batch_rows=512)
    jctx.create_table("dim", dim, chunked=True, batch_rows=512)
    join_sql = ("SELECT SUM(fact.val * dim.w) AS s, COUNT(*) AS n "
                "FROM fact JOIN dim ON fact.fk = dim.dk")
    j = fact.merge(dim, left_on="fk", right_on="dk")
    want = pd.DataFrame({"s": [(j.val * j.w).sum()], "n": [len(j)]})
    _exact(jctx.sql(join_sql, return_futures=False), want, "skewed join")
    recs = [r for r in ap.journal_rows() if r["action"] == "hint_record"]
    if not recs:
        return fail("skewed join did not record a re-plan hint")
    fp = recs[-1]["fingerprint"]
    # the hinted run must measure FASTER than its baseline; one noisy
    # sample is a strike, not a verdict — allow a second before failing
    verdict = None
    for _ in range(2):
        _exact(jctx.sql(join_sql, return_futures=False), want,
               "hinted join")
        vs = [r for r in ap.journal_rows()
              if r["action"] == "hint_verdict" and r["fingerprint"] == fp]
        if vs:
            verdict = vs[-1]
            break
    if verdict is None:
        return fail("hinted join never measured faster than its baseline")
    if _ctr("autopilot_hints_applied") < 1:
        return fail("hint was journaled but never applied")
    print(f"ok re-plan: {recs[-1]['trigger']} -> "
          f"{ap.get_hint(fp)['hints']} -> {verdict['verdict']}")

    # -- 5. kill switch: DSQL_AUTOPILOT=0 is a silent baseline -------------
    os.environ["DSQL_AUTOPILOT"] = "0"
    try:
        ap._reset_for_tests()
        before = {k: _ctr(k) for k in ("autopilot_ticks",
                                       "autopilot_mv_creates",
                                       "autopilot_hints_recorded")}
        off = Context()
        off.create_table("t", base)
        for _ in range(3):
            got = off.sql(HOT_SQL, return_futures=False)
        _exact(got, _oracle(base), "baseline aggregate")
        if ap.tick(off) != {}:
            return fail("tick acted under DSQL_AUTOPILOT=0")
        if ap.journal_rows():
            return fail("journal moved under DSQL_AUTOPILOT=0")
        if {k: _ctr(k) for k in before} != before:
            return fail("autopilot counters moved under DSQL_AUTOPILOT=0")
    finally:
        os.environ["DSQL_AUTOPILOT"] = "1"
    print("ok kill switch: DSQL_AUTOPILOT=0 ran silent, answers unchanged")

    print("autopilot smoke PASSED")
    return 0


if __name__ == "__main__":
    sys.exit(main())
